// Native SAR fast path: raw SubjectAccessReview JSON -> feature codes.
//
// This is the TPU framework's host-side hot loop in C++: it fuses the work
// of the Python pipeline (server/http.py get_authorizer_attributes ->
// server/authorizer.py record_to_cedar_resource -> compiler/table.py
// encode_request_codes) into one pass over the raw request bytes, producing
// the [n_slots] dictionary-code vector + extras list the device kernel
// consumes. Behavior parity with the Python path is enforced by
// tests/test_native_encoder.py (randomized differential tests).
//
// Designed for allocation-free steady state: the JSON DOM is pointer-linked
// nodes bump-allocated from a reusable arena, string values are views into
// the request buffer (escaped strings — rare in SARs — are materialized
// into arena-owned storage), and hash-map probe keys are composed into
// reused scratch buffers.
//
// Reference behaviors mirrored (cites are to /root/reference):
//   * SAR -> attributes: internal/server/server.go:163-309
//   * principal typing + group parents: internal/server/entities/user.go:35
//   * action/resource/non-resource/impersonation entities:
//     internal/server/authorizer/entitiy_builders.go:13-143
//   * authorizer gates (self-allow, system:* skip):
//     internal/server/authorizer/authorizer.go:38-57
//
// The activation-table blob is serialized by cedar_tpu/native/__init__.py
// (format documented there); canonical value-key strings must stay in sync
// with _canon() on the Python side.

#ifdef CEDAR_PY_GLUE
// Python.h first, per CPython convention. The *_pylist entries take the
// bodies list directly (via ctypes py_object through a PyDLL view of this
// library), eliminating the python-side join/fromiter/cumsum packing pass
// (~1.1us/request on the 1-core bench host). No libpython link is needed
// inside a CPython process; note the PyList_GET_* macros compile to
// struct-offset reads for the BUILD interpreter's ABI, which is why
// build.py keys the .so cache on the interpreter ABI tag (SOABI).
#define PY_SSIZE_T_CLEAN
#include <Python.h>
// Python.h drags in unistd.h, whose access(2) F_OK macro would shadow the
// encoder's own F_OK flag enum below
#undef F_OK
#endif

#include <arpa/inet.h>

#include <algorithm>
#include <atomic>
#include <charconv>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

using sv = std::string_view;

// ----------------------------------------------------------- tiny JSON DOM

struct JVal {
  enum Kind : uint8_t { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  sv str;        // STR payload
  sv key;        // member key when this node is an object member
  JVal *child = nullptr;  // first child (ARR/OBJ)
  JVal *next = nullptr;   // next sibling

  const JVal *get(sv k) const {
    if (kind != OBJ) return nullptr;
    // duplicate keys resolve to the last one, matching Python json.loads
    const JVal *found = nullptr;
    for (const JVal *c = child; c; c = c->next)
      if (c->key == k) found = c;
    return found;
  }
};

// Bump allocator with stable addresses, reusable across requests.
class Arena {
 public:
  JVal *alloc() {
    if (used_ == kChunk * chunks_.size()) chunks_.emplace_back(new JVal[kChunk]);
    JVal *v = &chunks_[used_ / kChunk][used_ % kChunk];
    ++used_;
    *v = JVal{};
    return v;
  }
  // arena-owned storage for escaped strings. Deque, NOT vector: growth must
  // never relocate the string objects — short strings store their bytes
  // inline (SSO), so a vector reallocation would dangle every sv previously
  // returned for a short escaped string (two escaped labels in one document
  // were enough to corrupt the first one's view).
  sv own(std::string &&s) {
    if (n_owned_ == owned_.size()) owned_.emplace_back();
    std::string &slot = owned_[n_owned_++];
    slot = std::move(s);
    return sv(slot);
  }
  void reset() {
    used_ = 0;
    n_owned_ = 0;
  }

 private:
  static constexpr size_t kChunk = 128;
  std::vector<std::unique_ptr<JVal[]>> chunks_;
  std::deque<std::string> owned_;
  size_t used_ = 0, n_owned_ = 0;
};

// bytes that continue the in-string fast scan (not quote, not backslash,
// not a raw control char — see JsonParser::string); constexpr so the
// per-byte hot loop carries no init guard
struct PlainTable {
  bool t[256] = {};
  constexpr PlainTable() {
    for (int c = 0; c < 256; ++c)
      t[c] = c >= 0x20 && c != '"' && c != '\\';
  }
};
constexpr PlainTable kPlain{};

class JsonParser {
 public:
  JsonParser(const char *p, size_t n, Arena &arena)
      : p_(p), end_(p + n), arena_(arena) {}

  JVal *parse() {
    JVal *v = value();
    if (!v) return nullptr;
    ws();
    if (p_ != end_) return nullptr;  // trailing garbage
    return v;
  }

 private:
  const char *p_, *end_;
  Arena &arena_;

  void ws() {
    while (p_ < end_ && (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' || *p_ == '\r'))
      ++p_;
  }
  bool lit(const char *s, size_t n) {
    if (size_t(end_ - p_) < n || memcmp(p_, s, n) != 0) return false;
    p_ += n;
    return true;
  }

  JVal *value() {
    ws();
    if (p_ >= end_) return nullptr;
    switch (*p_) {
      case '{': return container(true);
      case '[': return container(false);
      case '"': {
        JVal *v = arena_.alloc();
        v->kind = JVal::STR;
        if (!string(v->str)) return nullptr;
        return v;
      }
      case 't': {
        if (!lit("true", 4)) return nullptr;
        JVal *v = arena_.alloc();
        v->kind = JVal::BOOL;
        v->b = true;
        return v;
      }
      case 'f': {
        if (!lit("false", 5)) return nullptr;
        JVal *v = arena_.alloc();
        v->kind = JVal::BOOL;
        return v;
      }
      case 'n': {
        if (!lit("null", 4)) return nullptr;
        return arena_.alloc();
      }
      default: return number();
    }
  }

  JVal *number() {
    const char *start = p_;
    if (p_ < end_ && *p_ == '-') ++p_;
    if (p_ >= end_ || *p_ < '0' || *p_ > '9') return nullptr;
    while (p_ < end_ && ((*p_ >= '0' && *p_ <= '9') || *p_ == '.' || *p_ == 'e' ||
                         *p_ == 'E' || *p_ == '+' || *p_ == '-'))
      ++p_;
    JVal *v = arena_.alloc();
    v->kind = JVal::NUM;
    v->str = sv(start, size_t(p_ - start));  // token kept for the admission walk
    return v;
  }

  static void utf8_append(std::string &out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back(char(cp));
    } else if (cp < 0x800) {
      out.push_back(char(0xC0 | (cp >> 6)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(char(0xE0 | (cp >> 12)));
      out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(char(0xF0 | (cp >> 18)));
      out.push_back(char(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t &out) {
    if (end_ - p_ < 4) return false;
    out = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p_++;
      out <<= 4;
      if (c >= '0' && c <= '9') out |= uint32_t(c - '0');
      else if (c >= 'a' && c <= 'f') out |= uint32_t(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') out |= uint32_t(c - 'A' + 10);
      else return false;
    }
    return true;
  }

  // Fast path: no escapes -> a view into the input buffer, zero copies.
  // Raw control characters (< 0x20) inside strings are a parse error,
  // like the Python lane's strict json (a decision must never depend on
  // which lane a row takes — see utf8_valid). The scan stops on quote,
  // backslash, or control char via one load per byte from the constexpr
  // kPlain table (defined at namespace scope; zero init guards here).
  bool string(sv &out) {
    ++p_;  // opening quote
    const char *start = p_;
    while (p_ < end_ && kPlain.t[uint8_t(*p_)]) ++p_;
    if (p_ >= end_ || uint8_t(*p_) < 0x20) return false;
    if (*p_ == '"') {
      out = sv(start, size_t(p_ - start));
      ++p_;
      return true;
    }
    // slow path: materialize with escape processing
    std::string buf(start, size_t(p_ - start));
    while (p_ < end_) {
      char c = *p_;
      if (c == '"') {
        ++p_;
        out = arena_.own(std::move(buf));
        return true;
      }
      if (c == '\\') {
        ++p_;
        if (p_ >= end_) return false;
        char e = *p_++;
        switch (e) {
          case '"': buf.push_back('"'); break;
          case '\\': buf.push_back('\\'); break;
          case '/': buf.push_back('/'); break;
          case 'b': buf.push_back('\b'); break;
          case 'f': buf.push_back('\f'); break;
          case 'n': buf.push_back('\n'); break;
          case 'r': buf.push_back('\r'); break;
          case 't': buf.push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!hex4(cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF && end_ - p_ >= 6 && p_[0] == '\\' &&
                p_[1] == 'u') {
              const char *save = p_;
              p_ += 2;
              uint32_t lo;
              if (!hex4(lo)) return false;
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              } else {
                p_ = save;  // lone high surrogate; encode as-is (WTF-8)
              }
            }
            utf8_append(buf, cp);
            break;
          }
          default: return false;
        }
      } else {
        if (uint8_t(c) < 0x20) return false;  // raw control char in string
        buf.push_back(c);
        ++p_;
      }
    }
    return false;  // unterminated
  }

  // Nesting cap: a hostile body of 1MB of '[' would otherwise recurse once
  // per byte and overflow the native stack (no RecursionError here — the
  // whole webhook process would segfault). Beyond the cap the parse fails,
  // the row gets F_PARSE_ERROR, and the caller falls back to the Python
  // path, whose json.loads raises a handled RecursionError.
  static constexpr int kMaxDepth = 256;
  int depth_ = 0;

  JVal *container(bool is_obj) {
    if (depth_ >= kMaxDepth) return nullptr;
    ++depth_;
    JVal *v = container_body(is_obj);
    --depth_;
    return v;
  }

  JVal *container_body(bool is_obj) {
    ++p_;  // '{' or '['
    JVal *v = arena_.alloc();
    v->kind = is_obj ? JVal::OBJ : JVal::ARR;
    char close = is_obj ? '}' : ']';
    ws();
    if (p_ < end_ && *p_ == close) {
      ++p_;
      return v;
    }
    JVal *tail = nullptr;
    while (true) {
      sv key;
      if (is_obj) {
        ws();
        if (p_ >= end_ || *p_ != '"' || !string(key)) return nullptr;
        ws();
        if (p_ >= end_ || *p_ != ':') return nullptr;
        ++p_;
      }
      JVal *mv = value();
      if (!mv) return nullptr;
      mv->key = key;
      if (tail) tail->next = mv;
      else v->child = mv;
      tail = mv;
      ws();
      if (p_ < end_ && *p_ == ',') {
        ++p_;
        continue;
      }
      if (p_ < end_ && *p_ == close) {
        ++p_;
        return v;
      }
      return nullptr;
    }
  }
};

// --------------------------------------------------------- encoder tables

struct LikeComp {
  bool wild;
  std::string s;
};

struct LikeTest {
  int32_t lit;
  std::vector<LikeComp> comps;
};

struct CmpTest {
  int32_t lit;
  uint8_t op;  // 0 '<', 1 '<=', 2 '>', 3 '>='
  int64_t c;
};

// string hash usable for string_view probes without key construction
struct SvHash {
  using is_transparent = void;
  size_t operator()(sv s) const { return std::hash<sv>{}(s); }
  size_t operator()(const std::string &s) const { return std::hash<sv>{}(s); }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(sv a, sv b) const { return a == b; }
};

template <class V>
using SvMap = std::unordered_map<std::string, V, SvHash, SvEq>;

template <class V>
const V *sv_find(const SvMap<V> &m, sv key) {
#if defined(__cpp_lib_generic_unordered_lookup) && \
    __cpp_lib_generic_unordered_lookup >= 201811L
  auto it = m.find(key);
#else
  thread_local std::string scratch;
  scratch.assign(key.data(), key.size());
  auto it = m.find(scratch);
#endif
  return it == m.end() ? nullptr : &it->second;
}

// dyn template node (compiler/dyn.py): the probe value of a
// <slot>.contains(<template>) / <slot> == <template> hard expression,
// resolved per request.
struct Tmpl {
  uint8_t kind;     // 0 const canon, 2 record, 3 set,
                    // 4 slot (another request slot's value)
  uint8_t var = 0;  // slot: 0 principal, 1 action, 2 resource, 3 context
  std::string s;    // const: pre-canonicalized bytes
  std::vector<std::string> comps;  // slot: attribute path components
  std::vector<std::pair<std::string, Tmpl>> fields;  // record (names sorted)
                                                     // set: names unused
};

struct DynTest {
  uint8_t kind;  // 0 contains, 1 eq, 2 cmp, 3 containsAny, 4 containsAll
  uint8_t op;    // eq: 0 ==, 1 !=; cmp: 0 <, 1 <=, 2 >, 3 >=
  int32_t lit, ok_lit, err_lit;  // -1 when absent
  Tmpl tmpl;                // kinds 0-2
  std::vector<Tmpl> tmpls;  // kinds 3-4 (eagerly-evaluated element set)
};

struct TypeErrTest {
  int32_t lit;   // TYPE_ERR literal id
  uint8_t want;  // required value_key tag byte ('s', 'l', 'S', 'e', ...)
};

struct ScalarSlot {
  uint8_t var;       // 0 principal, 1 action, 2 resource, 3 context/other
  bool deep;         // multi-component path => value always missing (authz;
                     // the admission walk navigates `comps` instead)
  std::string attr;  // attribute path, components joined with \x1f
  std::vector<std::string> comps;  // split path (admission navigation)
  int32_t sidx;
  int32_t present_row;
  SvMap<int32_t> vocab;  // canon(value) -> row
  std::vector<LikeTest> likes;
  std::vector<CmpTest> cmps;
  SvMap<std::vector<int32_t>> set_has;
  std::vector<DynTest> dyns;
  // type-error indicators: active when the slot is PRESENT with a value
  // whose tag differs from `want` (in-vocab values ride the activation
  // rows; this list serves the vocab-miss branch, mirroring the Python
  // lane's value_tag extras)
  std::vector<TypeErrTest> type_errs;
};

struct Table {
  int32_t n_slots = 0;
  int32_t type_slot[3] = {-1, -1, -1};
  int32_t uid_slot[3] = {-1, -1, -1};
  std::vector<int32_t> anc_slots[3];
  SvMap<int32_t> type_map;  // v \x1f type
  SvMap<int32_t> uid_map;   // v \x1f type \x1f id
  SvMap<std::pair<int32_t, std::vector<int32_t>>> anc_map;
  std::vector<ScalarSlot> slots;
};

class BlobReader {
 public:
  BlobReader(const uint8_t *p, size_t n) : p_(p), end_(p + n) {}
  bool ok() const { return ok_; }

  uint8_t u8() { return ok_ && p_ < end_ ? *p_++ : (ok_ = false, 0); }
  int32_t i32() {
    if (!ok_ || end_ - p_ < 4) return ok_ = false, 0;
    int32_t v;
    memcpy(&v, p_, 4);
    p_ += 4;
    return v;
  }
  int64_t i64() {
    if (!ok_ || end_ - p_ < 8) return ok_ = false, 0;
    int64_t v;
    memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  std::string str() {
    int32_t n = i32();
    if (!ok_ || n < 0 || end_ - p_ < n) return ok_ = false, std::string();
    std::string s((const char *)p_, size_t(n));
    p_ += n;
    return s;
  }

 private:
  const uint8_t *p_, *end_;
  bool ok_ = true;
};

bool read_tmpl(BlobReader &r, Tmpl &t, int depth = 0) {
  if (depth > 8) return false;
  t.kind = r.u8();
  if (t.kind == 0) {
    t.s = r.str();
    return r.ok();
  }
  if (t.kind == 4) {
    t.var = r.u8();
    if (t.var > 3) return false;
    int32_t n = r.i32();
    if (!r.ok() || n < 1 || n > 32) return false;
    for (int32_t i = 0; i < n; ++i) t.comps.push_back(r.str());
    return r.ok();
  }
  if (t.kind != 2 && t.kind != 3) return false;
  int32_t n = r.i32();
  if (!r.ok() || n < 0 || n > 1024) return false;
  for (int32_t i = 0; i < n; ++i) {
    t.fields.emplace_back(t.kind == 2 ? r.str() : std::string(), Tmpl{});
    if (!read_tmpl(r, t.fields.back().second, depth + 1)) return false;
  }
  return r.ok();
}

Table *load_table(const uint8_t *blob, size_t len) {
  BlobReader r(blob, len);
  if (r.i32() != 0x43544234) return nullptr;  // "CTB4"
  auto t = std::make_unique<Table>();
  t->n_slots = r.i32();
  for (int v = 0; v < 3; ++v) {
    t->type_slot[v] = r.i32();
    t->uid_slot[v] = r.i32();
    int32_t n = r.i32();
    for (int32_t i = 0; i < n; ++i) t->anc_slots[v].push_back(r.i32());
  }
  int32_t n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    t->type_map[std::move(k)] = r.i32();
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    t->uid_map[std::move(k)] = r.i32();
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    int32_t row = r.i32();
    int32_t nl = r.i32();
    std::vector<int32_t> lits(size_t(nl >= 0 ? nl : 0));
    for (auto &l : lits) l = r.i32();
    t->anc_map[std::move(k)] = {row, std::move(lits)};
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    ScalarSlot s;
    s.var = r.u8();
    s.deep = r.u8() != 0;
    s.attr = r.str();
    {
      size_t start = 0;
      for (;;) {
        size_t sep = s.attr.find('\x1f', start);
        s.comps.push_back(s.attr.substr(
            start, sep == std::string::npos ? sep : sep - start));
        if (sep == std::string::npos) break;
        start = sep + 1;
      }
    }
    s.sidx = r.i32();
    s.present_row = r.i32();
    int32_t nv = r.i32();
    for (int32_t j = 0; j < nv; ++j) {
      std::string k = r.str();
      s.vocab[std::move(k)] = r.i32();
    }
    int32_t nl = r.i32();
    for (int32_t j = 0; j < nl; ++j) {
      LikeTest lt;
      lt.lit = r.i32();
      int32_t nc = r.i32();
      for (int32_t c = 0; c < nc; ++c) {
        LikeComp comp;
        comp.wild = r.u8() != 0;
        if (!comp.wild) comp.s = r.str();
        lt.comps.push_back(std::move(comp));
      }
      s.likes.push_back(std::move(lt));
    }
    int32_t ncmp = r.i32();
    for (int32_t j = 0; j < ncmp; ++j) {
      CmpTest c;
      c.lit = r.i32();
      c.op = r.u8();
      c.c = r.i64();
      s.cmps.push_back(c);
    }
    int32_t nsh = r.i32();
    for (int32_t j = 0; j < nsh; ++j) {
      std::string k = r.str();
      int32_t cnt = r.i32();
      std::vector<int32_t> lits(size_t(cnt >= 0 ? cnt : 0));
      for (auto &l : lits) l = r.i32();
      s.set_has[std::move(k)] = std::move(lits);
    }
    int32_t nd = r.i32();
    for (int32_t j = 0; j < nd; ++j) {
      DynTest d;
      d.kind = r.u8();
      if (d.kind > 4) return nullptr;
      d.op = r.u8();
      if (d.op > 3 || (d.kind != 2 && d.op > 1)) return nullptr;
      d.lit = r.i32();
      d.ok_lit = r.i32();
      d.err_lit = r.i32();
      if (d.kind >= 3) {
        int32_t nt = r.i32();
        if (!r.ok() || nt < 1 || nt > 256) return nullptr;
        for (int32_t k = 0; k < nt; ++k) {
          d.tmpls.emplace_back();
          if (!read_tmpl(r, d.tmpls.back())) return nullptr;
        }
      } else if (!read_tmpl(r, d.tmpl)) {
        return nullptr;
      }
      s.dyns.push_back(std::move(d));
    }
    int32_t nte = r.i32();
    for (int32_t j = 0; j < nte; ++j) {
      TypeErrTest te;
      te.lit = r.i32();
      te.want = r.u8();
      s.type_errs.push_back(te);
    }
    t->slots.push_back(std::move(s));
  }
  if (!r.ok()) return nullptr;
  return t.release();
}

// ------------------------------------------------------- like-glob matcher

// Mirrors cedar_tpu/lang/ast.py _match_components: DP over (component,
// position); components are literal chunks and wildcards.
bool like_match(const std::vector<LikeComp> &comps, sv s) {
  size_t n = s.size();
  thread_local std::vector<uint8_t> cur, next;
  cur.assign(n + 1, 0);
  next.assign(n + 1, 0);
  cur[0] = 1;
  for (const auto &comp : comps) {
    std::fill(next.begin(), next.end(), 0);
    if (comp.wild) {
      // wildcard: any reachable position reaches all later positions
      uint8_t reach = 0;
      for (size_t i = 0; i <= n; ++i) {
        reach |= cur[i];
        next[i] = reach;
      }
    } else {
      size_t m = comp.s.size();
      for (size_t i = 0; i + m <= n; ++i)
        if (cur[i] && memcmp(s.data() + i, comp.s.data(), m) == 0)
          next[i + m] = 1;
    }
    std::swap(cur, next);
  }
  return cur[n] != 0;
}

// --------------------------------------------------- canonical value keys

// Must stay byte-identical with _canon() in cedar_tpu/native/__init__.py.
// Strings are length-prefixed ("s<len>:<bytes>"): request-controlled bytes
// may contain the \x1f/\x1d structure separators, and without the prefix a
// crafted value could alias a different composite value's canon.
void canon_len_prefix(std::string &out, size_t n) {
  char buf[24];
  int w = snprintf(buf, sizeof buf, "%zu:", n);
  out.append(buf, size_t(w));
}

void canon_str_into(std::string &out, sv s) {
  out.push_back('s');
  canon_len_prefix(out, s.size());
  out.append(s.data(), s.size());
}

void canon_set_into(std::string &out, std::vector<std::string> &elems) {
  // sets canonicalize as a FROZENSET of element keys (lang/values.py
  // set_key): sort AND dedupe, or a duplicated element would change the key
  std::sort(elems.begin(), elems.end());
  elems.erase(std::unique(elems.begin(), elems.end()), elems.end());
  out += "S{";
  for (size_t i = 0; i < elems.size(); ++i) {
    if (i) out.push_back('\x1f');
    out += elems[i];
  }
  out.push_back('}');
}

// record with keys pre-sorted by the caller
std::string canon_record(
    std::initializer_list<std::pair<const char *, const std::string *>> fields) {
  std::string out = "R{";
  bool first = true;
  for (const auto &f : fields) {
    if (!first) out.push_back('\x1f');
    first = false;
    canon_len_prefix(out, strlen(f.first));
    out += f.first;
    out.push_back('\x1d');
    out += *f.second;
  }
  out.push_back('}');
  return out;
}

// -------------------------------------------------------- request features

// A slot value: authz-domain values are strings or sets-of-records.
struct Value {
  enum Kind { MISSING, STRV, SETV } kind = MISSING;
  sv str;
  std::vector<std::string> *elems = nullptr;  // element canon strings
};

struct Features {
  // principal
  sv p_type, p_id;
  std::vector<std::pair<sv, sv>> p_attrs;  // name / namespace
  std::vector<sv> groups;
  std::vector<std::string> extra_elem_canons;
  bool has_extra = false;
  // action
  sv verb;
  // resource entity
  sv r_type, r_id;
  std::vector<std::pair<sv, sv>> r_attrs;
  std::vector<std::string> label_elem_canons, field_elem_canons;
  bool has_label = false, has_field = false;
  // owned storage for composed strings (SA ids, resource paths, lowered keys)
  std::string own0, own1;

  void reset() {
    p_attrs.clear();
    groups.clear();
    extra_elem_canons.clear();
    has_extra = false;
    r_attrs.clear();
    label_elem_canons.clear();
    field_elem_canons.clear();
    has_label = has_field = false;
    own0.clear();
    own1.clear();
    p_type = p_id = verb = r_type = r_id = sv();
  }
};

constexpr sv kUser = "k8s::User";
constexpr sv kGroup = "k8s::Group";
constexpr sv kSA = "k8s::ServiceAccount";
constexpr sv kNode = "k8s::Node";
constexpr sv kPrincipalUID = "k8s::PrincipalUID";
constexpr sv kExtra = "k8s::Extra";
constexpr sv kResource = "k8s::Resource";
constexpr sv kNonResource = "k8s::NonResourceURL";
constexpr sv kAction = "k8s::Action";

int count_colons(sv s) {
  int n = 0;
  for (char c : s)
    if (c == ':') ++n;
  return n;
}

bool starts_with(sv s, sv prefix) {
  return s.size() >= prefix.size() && s.compare(0, prefix.size(), prefix) == 0;
}

sv str_field(const JVal *o, sv k) {
  const JVal *v = o ? o->get(k) : nullptr;
  return v && v->kind == JVal::STR ? v->str : sv();
}

// flags returned per request; mirrored in cedar_tpu/native/__init__.py
enum : uint8_t {
  F_OK = 0,
  F_PARSE_ERROR = 1,
  F_SELF_ALLOW_POLICIES = 2,
  F_SELF_ALLOW_RBAC = 3,
  F_SYSTEM_SKIP = 4,
  F_EXTRAS_OVERFLOW = 5,
  F_ADM_NS_SKIP = 6,  // admission: skipped namespace -> allow
  F_ADM_ERROR = 7,    // admission: shape/conversion issue -> python path
};

constexpr sv kAuthorizerIdentity = "system:authorizer:cedar-authorizer";

bool is_read_only(sv verb) {
  return verb == "get" || verb == "list" || verb == "watch";
}

void dedupe_children(const JVal *obj, std::vector<const JVal *> &out);

// Build all request features from the parsed SAR. Returns a gate flag or
// F_OK. Mirrors get_authorizer_attributes + record_to_cedar_resource.
// python-truthiness helpers for the SAR extraction. The Python lane skips
// FALSY optional blocks ("if ra:"), crashes on truthy wrong-typed ones
// (answering evaluation-error through its broad catch), and only ever
// proceeds with objects/strings of the expected type. Rows this lane
// flags re-run through the Python fallback, whose answer IS the oracle —
// over-flagging is parity-safe, silent coercion is not (the round-5
// type-flip fuzz found this lane evaluating wire shapes the Python lane
// refuses).
bool node_falsy(const JVal *v) {
  switch (v->kind) {
    case JVal::NUL: return true;
    case JVal::BOOL: return !v->b;
    case JVal::STR: return v->str.empty();
    case JVal::ARR:
    case JVal::OBJ: return v->child == nullptr;
    case JVal::NUM: return false;  // "0" is falsy in python; flagging the
      // rare numeric node routes to the fallback instead of raw-text
      // zero detection
  }
  return false;
}

// "if block:" gate: OBJ with children passes; falsy skips (nullptr);
// anything else marks bad (python would crash on attribute access)
const JVal *truthy_obj(const JVal *v, bool &bad) {
  if (!v) return nullptr;
  if (v->kind == JVal::OBJ && v->child) return v;
  if (node_falsy(v)) return nullptr;
  bad = true;
  return nullptr;
}

// field absent or a string; present-but-not-a-string routes the row to
// the Python fallback (shared by the SAR and admission lanes)
bool str_if_present(const JVal *o, sv k) {
  const JVal *v = o ? o->get(k) : nullptr;
  return !v || v->kind == JVal::STR;
}

// fused validate+extract: ONE child walk per field (the split
// sar_str_ok-then-str_field pattern cost a measured ~30% of encode).
// Absent -> empty; wrong-typed -> empty and bad set (python crashes)
sv str_field_vt(const JVal *o, sv k, bool &bad) {
  const JVal *v = o ? o->get(k) : nullptr;
  if (!v) return sv();
  if (v->kind != JVal::STR) {
    bad = true;
    return sv();
  }
  return v->str;
}

// selector SHAPE validation, shared by every resourceAttributes row:
// python parses label/field selectors inside "if ra:" BEFORE any verb
// branching, so even rows whose entity build ignores selectors (e.g.
// impersonation) crash python on flipped selector shapes — those rows
// must flag to the fallback here too
bool sar_selectors_ok(const JVal *ra) {
  for (sv sel_key : {sv("labelSelector"), sv("fieldSelector")}) {
    bool bad = false;
    const JVal *sel = truthy_obj(ra->get(sel_key), bad);
    if (bad) return false;
    const JVal *reqs = sel ? sel->get("requirements") : nullptr;
    if (!reqs) continue;
    if (reqs->kind != JVal::ARR) {
      if (!node_falsy(reqs)) return false;
      continue;
    }
    for (const JVal *rq = reqs->child; rq; rq = rq->next) {
      if (rq->kind != JVal::OBJ) return false;  // req.get crashes
      if (!str_if_present(rq, "operator") || !str_if_present(rq, "key"))
        return false;
      const JVal *vv = rq->get("values");
      if (!vv) continue;
      if (vv->kind != JVal::ARR) {
        if (!node_falsy(vv)) return false;
        continue;
      }
      for (const JVal *v = vv->child; v; v = v->next)
        if (v->kind != JVal::STR) return false;
    }
  }
  return true;
}

uint8_t build_features(const JVal *root, Features &f) {
  bool bad = false;
  const JVal *spec = truthy_obj(root->get("spec"), bad);
  if (bad) return F_PARSE_ERROR;  // truthy non-object: python crashes

  sv user_name = str_field_vt(spec, "user", bad);
  sv user_uid = str_field_vt(spec, "uid", bad);
  if (bad) return F_PARSE_ERROR;

  const JVal *ra =
      truthy_obj(spec ? spec->get("resourceAttributes") : nullptr, bad);
  const JVal *nra =
      truthy_obj(spec ? spec->get("nonResourceAttributes") : nullptr, bad);
  if (bad) return F_PARSE_ERROR;

  sv verb, ns, group, version, resource, subresource, name, path;
  bool resource_request = false;
  if (ra) {
    verb = str_field_vt(ra, "verb", bad);
    ns = str_field_vt(ra, "namespace", bad);
    group = str_field_vt(ra, "group", bad);
    version = str_field_vt(ra, "version", bad);
    resource = str_field_vt(ra, "resource", bad);
    subresource = str_field_vt(ra, "subresource", bad);
    name = str_field_vt(ra, "name", bad);
    if (bad || !sar_selectors_ok(ra)) return F_PARSE_ERROR;
    resource_request = true;
  }
  if (nra) {  // nonResourceAttributes wins last, like the Python builder
    path = str_field_vt(nra, "path", bad);
    verb = str_field_vt(nra, "verb", bad);
    if (bad) return F_PARSE_ERROR;
    resource_request = false;
  }

  // ------- authorizer gates (authorizer.go:38-57)
  if (user_name == kAuthorizerIdentity && is_read_only(verb)) {
    if (group == "cedar.k8s.aws" && resource == "policies")
      return F_SELF_ALLOW_POLICIES;
    if (group == "rbac.authorization.k8s.io") return F_SELF_ALLOW_RBAC;
  }
  if (starts_with(user_name, "system:") &&
      !starts_with(user_name, "system:serviceaccount:") &&
      !starts_with(user_name, "system:node:"))
    return F_SYSTEM_SKIP;

  // ------- principal (user.go:35)
  f.p_type = kUser;
  sv p_name = user_name;
  if (starts_with(user_name, "system:node:") && count_colons(user_name) == 2) {
    f.p_type = kNode;
    p_name = user_name.substr(strlen("system:node:"));
  }
  if (starts_with(user_name, "system:serviceaccount:") &&
      count_colons(user_name) == 3) {
    f.p_type = kSA;
    size_t a = strlen("system:serviceaccount:");
    size_t b = user_name.find(':', a);
    f.p_attrs.emplace_back("namespace", user_name.substr(a, b - a));
    p_name = user_name.substr(b + 1);
  }
  f.p_attrs.emplace_back("name", p_name);
  f.p_id = user_uid.empty() ? user_name : user_uid;

  const JVal *groups = spec ? spec->get("groups") : nullptr;
  if (groups) {
    if (groups->kind == JVal::ARR) {
      // python keeps every element; a non-string member crashes it
      // downstream — flag instead of silently dropping
      for (const JVal *g = groups->child; g; g = g->next) {
        if (g->kind != JVal::STR) return F_PARSE_ERROR;
        f.groups.push_back(g->str);
      }
    } else if (!node_falsy(groups)) {
      // python: tuple() of a non-iterable crashes; of a string tolerates
      // (character groups) — both classes answer via the fallback
      return F_PARSE_ERROR;
    }
  }

  const JVal *extra = spec ? spec->get("extra") : nullptr;
  if (extra && extra->kind != JVal::OBJ && !node_falsy(extra))
    return F_PARSE_ERROR;  // python: (extra).items() crashes
  if (extra && extra->kind == JVal::OBJ && extra->child) {
    f.has_extra = true;
    // json.loads dedupes raw keys (dict: first position, last value), then
    // convertExtra's {k.lower(): v} comprehension dedupes again on the
    // lower-cased key with the same dict semantics (server/http.py:74)
    std::vector<const JVal *> kids;
    dedupe_children(extra, kids);
    std::vector<std::pair<std::string, const JVal *>> lkids;
    for (const JVal *kv : kids) {
      // convertExtra lower-cases keys (server.go:205); canon applied after
      // the dedupe below
      std::string key;
      key.reserve(kv->key.size());
      for (char c : kv->key)
        key.push_back(c >= 'A' && c <= 'Z' ? char(c + 32) : c);
      bool replaced = false;
      for (auto &e : lkids)
        if (e.first == key) {
          e.second = kv;
          replaced = true;
          break;
        }
      if (!replaced) lkids.emplace_back(std::move(key), kv);
    }
    for (auto &e : lkids) {
      const JVal *kv = e.second;
      std::vector<std::string> vals;
      if (kv->kind == JVal::ARR) {
        for (const JVal *v = kv->child; v; v = v->next) {
          // python: tuple(v) keeps every element; non-strings crash the
          // canon downstream — flag instead of silently dropping
          if (v->kind != JVal::STR) return F_PARSE_ERROR;
          std::string c;
          canon_str_into(c, v->str);
          vals.push_back(std::move(c));
        }
      } else {
        // python: tuple() of a non-list crashes or chars-splits a string
        return F_PARSE_ERROR;
      }
      std::string kc, vset;
      canon_str_into(kc, e.first);
      canon_set_into(vset, vals);
      f.extra_elem_canons.push_back(
          canon_record({{"key", &kc}, {"values", &vset}}));
    }
  }

  f.verb = verb;

  // ------- resource entity (entitiy_builders.go)
  if (resource_request && verb == "impersonate") {
    if (resource == "serviceaccounts") {
      f.r_type = kSA;
      f.own0.assign("system:serviceaccount:");
      f.own0.append(ns.data(), ns.size());
      f.own0.push_back(':');
      f.own0.append(name.data(), name.size());
      f.r_id = f.own0;
      f.r_attrs.emplace_back("name", name);
      f.r_attrs.emplace_back("namespace", ns);
    } else if (resource == "uids") {
      f.r_type = kPrincipalUID;
      f.r_id = name;
    } else if (resource == "users") {
      f.r_type = kUser;
      sv rname = name;
      if (starts_with(name, "system:node:") && count_colons(name) == 2) {
        f.r_type = kNode;
        rname = name.substr(strlen("system:node:"));
      }
      f.r_attrs.emplace_back("name", rname);
      f.r_id = name;
    } else if (resource == "groups") {
      f.r_type = kGroup;
      f.r_id = name;
      f.r_attrs.emplace_back("name", name);
    } else if (resource == "userextras") {
      f.r_type = kExtra;
      f.r_id = subresource;
      f.r_attrs.emplace_back("key", subresource);
      if (!name.empty()) f.r_attrs.emplace_back("value", name);
    } else {
      f.r_type = sv();
      f.r_id = sv();
    }
  } else if (resource_request) {
    f.r_type = kResource;
    std::string &p = f.own0;
    if (group.empty()) {
      p.assign("/api/");
    } else {
      p.assign("/apis/");
      p.append(group.data(), group.size());
      p.push_back('/');
    }
    p.append(version.data(), version.size());
    if (!ns.empty()) {
      p.append("/namespaces/");
      p.append(ns.data(), ns.size());
    }
    p.push_back('/');
    p.append(resource.data(), resource.size());
    if (!name.empty()) {
      p.push_back('/');
      p.append(name.data(), name.size());
    }
    if (!subresource.empty()) {
      p.push_back('/');
      p.append(subresource.data(), subresource.size());
    }
    f.r_id = p;
    f.r_attrs.emplace_back("apiGroup", group);
    f.r_attrs.emplace_back("resource", resource);
    if (!name.empty()) f.r_attrs.emplace_back("name", name);
    if (!subresource.empty()) f.r_attrs.emplace_back("subresource", subresource);
    if (!ns.empty()) f.r_attrs.emplace_back("namespace", ns);

    // selectors (server.go:221-309); shapes are already gated by
    // sar_selectors_ok above — tolerant reads here cannot be reached
    // with python-crashing values
    const JVal *ls = ra->get("labelSelector");
    const JVal *reqs =
        ls && ls->kind == JVal::OBJ ? ls->get("requirements") : nullptr;
    if (reqs && reqs->kind == JVal::ARR && reqs->child) {
      for (const JVal *rq = reqs->child; rq; rq = rq->next) {
        if (rq->kind != JVal::OBJ) continue;
        sv op = str_field(rq, "operator");
        const char *mapped = nullptr;
        if (op == "In") mapped = "in";
        else if (op == "NotIn") mapped = "notin";
        else if (op == "Exists") mapped = "exists";
        else if (op == "DoesNotExist") mapped = "!";
        if (!mapped) continue;  // invalid operators dropped
        std::vector<std::string> vals;
        const JVal *vv = rq->get("values");
        if (vv && vv->kind == JVal::ARR)
          for (const JVal *v = vv->child; v; v = v->next)
            if (v->kind == JVal::STR) {
              std::string c;
              canon_str_into(c, v->str);
              vals.push_back(std::move(c));
            }
        std::string key, ops, vset;
        canon_str_into(key, str_field(rq, "key"));
        canon_str_into(ops, mapped);
        canon_set_into(vset, vals);
        f.label_elem_canons.push_back(canon_record(
            {{"key", &key}, {"operator", &ops}, {"values", &vset}}));
      }
      f.has_label = !f.label_elem_canons.empty();
    }
    const JVal *fs = ra->get("fieldSelector");
    const JVal *freqs =
        fs && fs->kind == JVal::OBJ ? fs->get("requirements") : nullptr;
    if (freqs && freqs->kind == JVal::ARR && freqs->child) {
      for (const JVal *rq = freqs->child; rq; rq = rq->next) {
        if (rq->kind != JVal::OBJ) continue;
        sv op = str_field(rq, "operator");
        const JVal *vv = rq->get("values");
        size_t nvals = 0;
        const JVal *first_val = nullptr;
        if (vv && vv->kind == JVal::ARR)
          for (const JVal *v = vv->child; v; v = v->next) {
            if (!first_val) first_val = v;
            ++nvals;
          }
        const char *mapped = nullptr;
        if (op == "In" && nvals == 1) mapped = "=";
        else if (op == "NotIn" && nvals == 1) mapped = "!=";
        if (!mapped) continue;
        sv val = first_val && first_val->kind == JVal::STR ? first_val->str : sv();
        std::string fld, ops, vc;
        canon_str_into(fld, str_field(rq, "key"));
        canon_str_into(ops, mapped);
        canon_str_into(vc, val);
        f.field_elem_canons.push_back(canon_record(
            {{"field", &fld}, {"operator", &ops}, {"value", &vc}}));
      }
      f.has_field = !f.field_elem_canons.empty();
    }
  } else {
    f.r_type = kNonResource;
    f.r_id = path;
    f.r_attrs.emplace_back("path", path);
  }
  return F_OK;
}

// ------------------------------------------------------------ slot lookup

struct ExtrasOut {
  int32_t *buf;
  int32_t cap;
  int32_t n = 0;
  bool overflow = false;
  void push(int32_t v) {
    if (n < cap) buf[n++] = v;
    else overflow = true;
  }
};

// Resolve a dyn template into the probe's canonical value key.
// `slot_canon` is `bool(uint8_t var, const vector<string> &comps,
// string &out)` appending ANY request slot's canonical value (false when
// the chain doesn't resolve — a Cedar attribute-access error). Returns
// false on any error — the caller activates the test's err_lit, mirroring
// the interpreter raising from the same expression.
template <class S>
bool tmpl_canon(const Tmpl &t, S &&slot_canon, std::string &out) {
  if (t.kind == 0) {  // pre-canonicalized constant
    out += t.s;
    return true;
  }
  if (t.kind == 4)  // another request slot's value
    return slot_canon(t.var, t.comps, out);
  if (t.kind == 3) {  // set: canonicalize children, sort + dedupe
    std::vector<std::string> es;
    es.reserve(t.fields.size());
    for (const auto &f : t.fields) {
      std::string ec;
      if (!tmpl_canon(f.second, slot_canon, ec)) return false;
      es.push_back(std::move(ec));
    }
    canon_set_into(out, es);
    return true;
  }
  // record: field names pre-sorted at serialize time (canon_cval parity)
  out += "R{";
  for (size_t i = 0; i < t.fields.size(); ++i) {
    if (i) out.push_back('\x1f');
    canon_len_prefix(out, t.fields[i].first.size());
    out += t.fields[i].first;
    out.push_back('\x1d');
    if (!tmpl_canon(t.fields[i].second, slot_canon, out)) return false;
  }
  out.push_back('}');
  return true;
}

// Parse a canonical Long ("l<decimal>") back to its value; false for any
// other canon tag (the operand is not a Cedar Long).
bool canon_long(const std::string &c, long long *out) {
  if (c.size() < 2 || c[0] != 'l') return false;
  const char *b = c.data() + 1, *e = c.data() + c.size();
  auto res = std::from_chars(b, e, *out);
  return res.ec == std::errc() && res.ptr == e;
}

// Evaluate a slot's dyn tests.
//   contains (kind 0): needs the slot's element canons (`elems`; nullptr =>
//     the slot path is missing / not a set: the test errors, exactly where
//     the interpreter raises evaluating the same expression).
//   eq/neq (kind 1): needs the slot value's full canonical key
//     (`self_canon`; nullptr => missing attribute: access error). Equal
//     Cedar values have equal canons (the canon keys the vocab), and
//     cross-type ==/!= is False/True never an error, so a byte compare IS
//     Cedar equality.
//   cmp (kind 2): both canons must be Longs ("l<decimal>"); anything else
//     is the interpreter's type error.
//   containsAny/All (kinds 3/4): like contains, but over an EAGERLY
//     resolved element-template set — any resolution failure errors the
//     whole test before membership is judged, matching Cedar's eager
//     argument evaluation.
template <class S>
void eval_dyns(const ScalarSlot &s, const std::vector<std::string> *elems,
               const std::string *self_canon, S &&slot_canon,
               ExtrasOut &extras, std::string &scratch) {
  for (const auto &d : s.dyns) {
    if (d.kind == 1) {  // eq / neq: canon byte compare
      if (!self_canon) {
        if (d.err_lit >= 0) extras.push(d.err_lit);
        continue;
      }
      scratch.clear();
      if (!tmpl_canon(d.tmpl, slot_canon, scratch)) {
        if (d.err_lit >= 0) extras.push(d.err_lit);
        continue;
      }
      if (d.ok_lit >= 0) extras.push(d.ok_lit);
      bool hit = *self_canon == scratch;
      if (d.op) hit = !hit;  // != (cross-type != is True)
      if (hit && d.lit >= 0) extras.push(d.lit);
      continue;
    }
    if (d.kind == 2) {  // ordered cmp: both sides must be Longs
      if (!self_canon) {
        if (d.err_lit >= 0) extras.push(d.err_lit);
        continue;
      }
      scratch.clear();
      long long a, b;
      if (!tmpl_canon(d.tmpl, slot_canon, scratch) ||
          !canon_long(*self_canon, &a) || !canon_long(scratch, &b)) {
        // missing attr OR a non-Long operand: Cedar's < <= > >= are
        // defined on Longs only — the interpreter raises a type error
        if (d.err_lit >= 0) extras.push(d.err_lit);
        continue;
      }
      if (d.ok_lit >= 0) extras.push(d.ok_lit);
      bool hit = d.op == 0   ? a < b
                 : d.op == 1 ? a <= b
                 : d.op == 2 ? a > b
                             : a >= b;
      if (hit && d.lit >= 0) extras.push(d.lit);
      continue;
    }
    if (!elems) {
      if (d.err_lit >= 0) extras.push(d.err_lit);
      continue;
    }
    if (d.kind >= 3) {  // containsAny (3) / containsAll (4): Cedar
      // evaluates the argument set EAGERLY — every template must resolve
      // (a later failure errors the whole test, so no early exit on a
      // decided any/all), but membership is pure, so each probe is
      // tested from the shared scratch as it resolves: no allocation
      bool failed = false, any = false, all = true;
      for (const auto &t : d.tmpls) {
        scratch.clear();
        if (!tmpl_canon(t, slot_canon, scratch)) {
          failed = true;
          break;
        }
        bool member = false;
        for (const auto &ec : *elems)
          if (ec == scratch) {
            member = true;
            break;
          }
        any = any || member;
        all = all && member;
      }
      if (failed) {
        if (d.err_lit >= 0) extras.push(d.err_lit);
        continue;
      }
      if (d.ok_lit >= 0) extras.push(d.ok_lit);
      bool hit = d.kind == 3 ? any : all;
      if (hit && d.lit >= 0) extras.push(d.lit);
      continue;
    }
    scratch.clear();
    if (!tmpl_canon(d.tmpl, slot_canon, scratch)) {
      if (d.err_lit >= 0) extras.push(d.err_lit);
      continue;
    }
    if (d.ok_lit >= 0) extras.push(d.ok_lit);
    bool member = false;
    for (const auto &ec : *elems)
      if (ec == scratch) {
        member = true;
        break;
      }
    if (member && d.lit >= 0) extras.push(d.lit);
  }
}

// Resolve one FLAT attribute of an authz request variable — the single
// resolution rule shared by the vocab path (slot_value) and the template
// slot-leaf path (sar_slot_canon), so the two can never diverge on which
// attributes exist.
Value resolve_sar_attr(Features &f, uint8_t var, bool deep, sv attr) {
  Value v;
  if (deep || var == 3) return v;  // context is empty for authz; deep
                                   // paths never resolve in this domain
  if (var == 0) {  // principal
    for (const auto &kv : f.p_attrs)
      if (kv.first == attr) {
        v.kind = Value::STRV;
        v.str = kv.second;
        return v;
      }
    if (attr == sv("extra") && f.has_extra) {
      v.kind = Value::SETV;
      v.elems = &f.extra_elem_canons;
    }
    return v;
  }
  if (var == 1) return v;  // action entities carry no attributes
  // resource
  for (const auto &kv : f.r_attrs)
    if (kv.first == attr) {
      v.kind = Value::STRV;
      v.str = kv.second;
      return v;
    }
  if (attr == sv("labelSelector") && f.has_label) {
    v.kind = Value::SETV;
    v.elems = &f.label_elem_canons;
  } else if (attr == sv("fieldSelector") && f.has_field) {
    v.kind = Value::SETV;
    v.elems = &f.field_elem_canons;
  }
  return v;
}

Value slot_value(Features &f, const ScalarSlot &s) {
  return resolve_sar_attr(f, s.var, s.deep, sv(s.attr));
}

// Resolve a template SLOT leaf for the authz domain: (var, single flat
// attribute) -> append the value's canonical key. Shares resolve_sar_attr
// with slot_value; deep chains, context, and action never resolve here —
// the interpreter errors on the same accesses (authz attributes are flat).
bool sar_slot_canon(Features &f, uint8_t var,
                    const std::vector<std::string> &comps, std::string &out) {
  if (comps.size() != 1) return false;
  Value v = resolve_sar_attr(f, var, false, sv(comps[0]));
  if (v.kind == Value::STRV) {
    canon_str_into(out, v.str);
    return true;
  }
  if (v.kind == Value::SETV) {
    canon_set_into(out, *v.elems);
    return true;
  }
  return false;
}

void encode_one(const Table &t, Features &f, int32_t *codes, ExtrasOut &extras,
                std::string &scratch) {
  for (int32_t i = 0; i < t.n_slots; ++i) codes[i] = 0;

  const sv types[3] = {f.p_type, kAction, f.r_type};
  const sv ids[3] = {f.p_id, f.verb, f.r_id};

  const char vtag[3] = {'0', '1', '2'};
  for (int v = 0; v < 3; ++v) {
    if (t.type_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      const int32_t *row = sv_find(t.type_map, scratch);
      codes[t.type_slot[v]] = row ? *row : 0;
    }
    if (t.uid_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      scratch.push_back('\x1f');
      scratch.append(ids[v].data(), ids[v].size());
      const int32_t *row = sv_find(t.uid_map, scratch);
      codes[t.uid_slot[v]] = row ? *row : 0;
    }
  }

  // principal ancestors: group parent entities (user.go:23-27). Actions and
  // resources have no parents in the authz domain.
  if (!t.anc_slots[0].empty() && !f.groups.empty()) {
    size_t filled = 0;
    const auto &slots = t.anc_slots[0];
    for (sv g : f.groups) {
      scratch.assign("0\x1f");
      scratch.append(kGroup.data(), kGroup.size());
      scratch.push_back('\x1f');
      scratch.append(g.data(), g.size());
      const auto *entry = sv_find(t.anc_map, scratch);
      if (!entry || entry->first == 0) continue;
      if (filled < slots.size()) {
        codes[slots[filled++]] = entry->first;
      } else {
        for (int32_t lid : entry->second) extras.push(lid);
      }
    }
  }

  std::string vcanon;  // the slot value's canon: vocab key + dyn eq operand
  for (const auto &s : t.slots) {
    Value v = slot_value(f, s);
    vcanon.clear();
    const std::string *self = nullptr;
    if (v.kind == Value::STRV) {
      canon_str_into(vcanon, v.str);
      self = &vcanon;
    } else if (v.kind == Value::SETV) {
      canon_set_into(vcanon, *v.elems);  // sorts elems in place (stable key)
      self = &vcanon;
    }
    if (!s.dyns.empty()) {
      auto slot_canon = [&f](uint8_t var, const std::vector<std::string> &c,
                             std::string &out) {
        return sar_slot_canon(f, var, c, out);
      };
      eval_dyns(s, v.kind == Value::SETV ? v.elems : nullptr, self,
                slot_canon, extras, scratch);
    }
    if (v.kind == Value::MISSING) continue;

    const int32_t *row = sv_find(s.vocab, vcanon);
    if (row) {
      codes[s.sidx] = *row;
    } else {
      codes[s.sidx] = s.present_row;
      if (v.kind == Value::STRV) {
        for (const auto &lt : s.likes)
          if (like_match(lt.comps, v.str)) extras.push(lt.lit);
        // cmp tests only apply to longs; authz values are strings
      }
      if (!s.type_errs.empty()) {
        // authz slot values are strings or string sets
        const uint8_t tag = v.kind == Value::STRV ? 's' : 'S';
        for (const auto &te : s.type_errs)
          if (te.want != tag) extras.push(te.lit);
      }
    }
    if (v.kind == Value::SETV && !s.set_has.empty()) {
      for (const auto &ec : *v.elems) {
        const auto *lits = sv_find(s.set_has, ec);
        if (lits)
          for (int32_t lid : *lits) extras.push(lid);
      }
    }
  }
}

// ======================= admission encoding ==============================
// Raw AdmissionReview JSON -> feature codes over the same activation table,
// mirroring cedar_tpu/entities/admission.py + server/admission.py (reference
// internal/server/entities/admission.go:160-369). Rows the native walk
// cannot prove identical to the Python path (unsupported leaf types, parse
// quirks, pathological shapes) are flagged for the exact Python fallback.

struct CVal {
  enum Kind : uint8_t { STRV, LONGV, BOOLV, IPV, SETV, RECV, ENTV } kind = STRV;
  sv str;       // STRV payload / IPV raw text / ENTV id
  sv ent_type;  // ENTV type
  int64_t l = 0;
  bool b = false;
  std::vector<std::pair<sv, CVal *>> fields;  // RECV
  std::vector<CVal *> elems;                  // SETV
  // memoized canonical key: several table slots (and the dyn template
  // resolver) canonicalize the SAME node per request — labels-bearing
  // admission objects paid ~1us/entry re-canonicalizing across slots.
  // Valid iff canon_done; make() clears the flag, the string keeps its
  // capacity across pool reuse.
  std::string canon;
  bool canon_done = false;
};

class CPool {
 public:
  CVal *make(CVal::Kind k) {
    if (used_ == pool_.size()) pool_.emplace_back();
    CVal *v = &pool_[used_++];
    v->kind = k;
    v->str = sv();
    v->ent_type = sv();
    v->l = 0;
    v->b = false;
    v->fields.clear();
    v->elems.clear();
    v->canon_done = false;
    return v;
  }
  void reset() { used_ = 0; }

 private:
  std::deque<CVal> pool_;
  size_t used_ = 0;
};

// g/v/k-conditional map attributes; MUST stay in sync with
// KNOWN_KEY_VALUE_STRING_MAP_ATTRIBUTES / .._SLICE_.. in
// cedar_tpu/entities/admission.py (reference admission.go:195-295).
const SvMap<char> &kv_string_attrs() {
  static const SvMap<char> m = [] {
    SvMap<char> t;
    auto add = [&](const char *g, const char *v, const char *k,
                   std::initializer_list<const char *> attrs) {
      for (const char *a : attrs) {
        std::string key;
        (((key += g) += '\x1f') += v) += '\x1f';
        ((key += k) += '\x1f') += a;
        t[std::move(key)] = 1;
      }
    };
    add("core", "v1", "ConfigMap", {"data", "binaryData"});
    add("core", "v1", "CSIPersistentVolumeSource", {"volumeAttributes"});
    add("core", "v1", "CSIVolumeSource", {"volumeAttributes"});
    add("core", "v1", "FlexPersistentVolumeSource", {"options"});
    add("core", "v1", "FlexVolumeSource", {"options"});
    add("core", "v1", "PersistentVolumeClaimStatus",
        {"allocatedResourceStatuses"});
    add("core", "v1", "Pod", {"nodeSelector"});
    add("core", "v1", "ReplicationController", {"selector"});
    add("core", "v1", "Secret", {"data", "stringData"});
    add("core", "v1", "Service", {"selector"});
    add("discovery", "v1", "Endpoint", {"deprecatedTopology"});
    add("node", "v1", "Scheduling", {"nodeSelectors"});
    add("storage", "v1", "StorageClass", {"parameters"});
    add("storage", "v1", "VolumeAttachmentStatus", {"attachmentMetadata"});
    add("meta", "v1", "LabelSelector", {"matchLabels"});
    add("meta", "v1", "ObjectMeta", {"annotations", "labels"});
    return t;
  }();
  return m;
}

const SvMap<char> &kv_slice_attrs() {
  static const SvMap<char> m = [] {
    SvMap<char> t;
    auto add = [&](const char *g, const char *v, const char *k, const char *a) {
      std::string key;
      (((key += g) += '\x1f') += v) += '\x1f';
      ((key += k) += '\x1f') += a;
      t[std::move(key)] = 1;
    };
    add("authentication", "v1", "UserInfo", "extra");
    add("authorization", "v1", "SubjectAccessReview", "extra");
    add("certificates", "v1", "CertificateSigningRequest", "extra");
    return t;
  }();
  return m;
}

bool is_ip_key(sv k) {
  return k == "podIP" || k == "clusterIP" || k == "loadBalancerIP" ||
         k == "hostIP" || k == "ip" || k == "podIPs" || k == "hostIPs";
}

// python int(str): optional surrounding whitespace, optional sign, digits
// with single underscores BETWEEN digits. Returns false when python would
// raise ValueError.
bool py_int_parse(sv s, long long *out) {
  size_t a = 0, b = s.size();
  auto is_ws = [](char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
           c == '\v';
  };
  while (a < b && is_ws(s[a])) ++a;
  while (b > a && is_ws(s[b - 1])) --b;
  if (a == b) return false;
  bool neg = false;
  if (s[a] == '+' || s[a] == '-') {
    neg = s[a] == '-';
    ++a;
  }
  if (a == b) return false;
  long long v = 0;
  bool last_digit = false;
  for (size_t i = a; i < b; ++i) {
    char c = s[i];
    if (c == '_') {
      if (!last_digit || i + 1 == b) return false;
      last_digit = false;
      continue;
    }
    if (c < '0' || c > '9') return false;
    if (v > (1ll << 40)) return false;  // far past any prefix length
    v = v * 10 + (c - '0');
    last_digit = true;
  }
  if (!last_digit) return false;
  *out = neg ? -v : v;
  return true;
}

// 0 = not an ip (python IPAddr.parse raises -> raw string kept),
// 1 = ip, 2 = can't prove parity (scoped IPv6 etc.) -> python fallback
int classify_ip(sv s) {
  sv addr = s;
  size_t slash = s.rfind('/');
  if (slash != sv::npos) {
    addr = s.substr(0, slash);
    sv pfx = s.substr(slash + 1);
    long long p;
    if (!py_int_parse(pfx, &p)) return 0;  // int(p) raises -> raw string
    bool v6 = addr.find(':') != sv::npos;
    if (p < 0 || p > (v6 ? 128 : 32)) return 0;  // bad prefix -> raw string
  }
  if (addr.find('%') != sv::npos) return 2;  // python 3.9+ parses zone ids
  if (addr.find(':') != sv::npos) {
    char buf[16];
    std::string z(addr);
    if (inet_pton(AF_INET6, z.c_str(), buf) != 1) return 0;
    // only admit v6 spellings already in canonical (inet_ntop) form: the
    // IPV canon (canon_cval) byte-compares address text as the equality
    // basis, so "0:0:0:0:0:0:0:1" must not be provable — it would compare
    // unequal to "::1" while python's parsed addresses compare equal
    char txt[INET6_ADDRSTRLEN];
    if (!inet_ntop(AF_INET6, buf, txt, sizeof txt)) return 2;
    return z == txt ? 1 : 2;
  }
  // strict dotted-quad: 4 decimal octets, 0-255, no leading zeros
  int octets = 0;
  size_t i = 0;
  while (i < addr.size()) {
    size_t start = i;
    int v = 0;
    while (i < addr.size() && addr[i] >= '0' && addr[i] <= '9') {
      v = v * 10 + (addr[i] - '0');
      if (v > 255) return 0;
      ++i;
    }
    size_t len = i - start;
    if (len == 0 || len > 3) return 0;
    if (len > 1 && addr[start] == '0') return 0;
    ++octets;
    if (i == addr.size()) break;
    if (addr[i] != '.') return 0;
    ++i;
    if (i == addr.size()) return 0;  // trailing dot
  }
  return octets == 4 ? 1 : 0;
}

struct AdmCtx {
  CPool *cp;
  sv group, kversion, kkind;  // request g/v/k for the known-map tables
  bool error = false;         // -> F_ADM_ERROR (python fallback re-raises)
};

void dedupe_insert(std::vector<std::pair<sv, CVal *>> &fields, sv key,
                   CVal *val) {
  // python dicts deduplicate JSON keys (last value wins)
  for (auto &f : fields)
    if (f.first == key) {
      f.second = val;
      return;
    }
  fields.emplace_back(key, val);
}

// Resolve duplicate JSON keys BEFORE any per-value filtering: python's
// json.loads builds the dict first (last value wins, whatever its type),
// then the walk filters — filtering before dedup would let a skipped later
// duplicate resurrect an earlier value.
void dedupe_children(const JVal *obj,
                     std::vector<const JVal *> &out) {
  out.clear();
  for (const JVal *kv = obj->child; kv; kv = kv->next) {
    bool replaced = false;
    for (auto &existing : out)
      if (existing->key == kv->key) {
        existing = kv;
        replaced = true;
        break;
      }
    if (!replaced) out.push_back(kv);
  }
}

CVal *key_value_set(AdmCtx &c, const JVal *obj) {
  // map[string]string -> Set<{key, value}>; non-string values skip the key
  CVal *s = c.cp->make(CVal::SETV);
  std::vector<const JVal *> kids;
  dedupe_children(obj, kids);
  for (const JVal *kv : kids) {
    if (kv->kind != JVal::STR) continue;
    CVal *r = c.cp->make(CVal::RECV);
    CVal *k = c.cp->make(CVal::STRV);
    k->str = kv->key;
    CVal *v = c.cp->make(CVal::STRV);
    v->str = kv->str;
    r->fields.emplace_back("key", k);
    r->fields.emplace_back("value", v);
    s->elems.push_back(r);
  }
  return s;
}

CVal *key_value_slice_set(AdmCtx &c, const JVal *obj) {
  // map[string][]string -> Set<{key, value: Set<String>}>
  CVal *s = c.cp->make(CVal::SETV);
  std::vector<const JVal *> kids;
  dedupe_children(obj, kids);
  for (const JVal *kv : kids) {
    if (kv->kind != JVal::ARR) continue;
    CVal *vals = c.cp->make(CVal::SETV);
    for (const JVal *e = kv->child; e; e = e->next)
      if (e->kind == JVal::STR) {
        CVal *ev = c.cp->make(CVal::STRV);
        ev->str = e->str;
        vals->elems.push_back(ev);
      }
    CVal *r = c.cp->make(CVal::RECV);
    CVal *k = c.cp->make(CVal::STRV);
    k->str = kv->key;
    r->fields.emplace_back("key", k);
    r->fields.emplace_back("value", vals);
    s->elems.push_back(r);
  }
  return s;
}

CVal *adm_walk(AdmCtx &c, int depth, sv key, const JVal *v) {
  if (depth == 0) {
    c.error = true;  // python raises "max depth reached"
    return nullptr;
  }
  switch (v->kind) {
    case JVal::NUL:
      return nullptr;
    case JVal::OBJ: {
      thread_local std::string k;
      k.assign(c.group.data(), c.group.size());
      k += '\x1f';
      k.append(c.kversion.data(), c.kversion.size());
      k += '\x1f';
      k.append(c.kkind.data(), c.kkind.size());
      k += '\x1f';
      k.append(key.data(), key.size());
      if (sv_find(kv_string_attrs(), k)) return key_value_set(c, v);
      if (sv_find(kv_slice_attrs(), k)) return key_value_slice_set(c, v);
      if (key == "labels" || key == "annotations") return key_value_set(c, v);
      CVal *r = c.cp->make(CVal::RECV);
      std::vector<const JVal *> kids;
      dedupe_children(v, kids);
      for (const JVal *kv : kids) {
        CVal *val = adm_walk(c, depth - 1, kv->key, kv);
        if (c.error) return nullptr;
        if (!val) continue;  // nulls and empty nested records are skipped
        r->fields.emplace_back(kv->key, val);
      }
      if (r->fields.empty()) return nullptr;
      return r;
    }
    case JVal::ARR: {
      CVal *s = c.cp->make(CVal::SETV);
      for (const JVal *it = v->child; it; it = it->next) {
        CVal *e = adm_walk(c, depth - 1, key, it);
        if (c.error) return nullptr;
        if (e) s->elems.push_back(e);
      }
      return s;
    }
    case JVal::STR: {
      if (is_ip_key(key)) {
        int cls = classify_ip(v->str);
        if (cls == 2) {
          c.error = true;
          return nullptr;
        }
        if (cls == 1) {
          CVal *x = c.cp->make(CVal::IPV);
          x->str = v->str;
          return x;
        }
      }
      CVal *x = c.cp->make(CVal::STRV);
      x->str = v->str;
      return x;
    }
    case JVal::BOOL: {
      CVal *x = c.cp->make(CVal::BOOLV);
      x->b = v->b;
      return x;
    }
    case JVal::NUM: {
      sv t = v->str;
      for (char ch : t)
        if (ch == '.' || ch == 'e' || ch == 'E') {
          c.error = true;  // python json gives float -> walk raises
          return nullptr;
        }
      int64_t x = 0;
      auto res = std::from_chars(t.data(), t.data() + t.size(), x);
      if (res.ec != std::errc() || res.ptr != t.data() + t.size()) {
        c.error = true;  // out-of-int64 (python bigint) or malformed
        return nullptr;
      }
      CVal *n = c.cp->make(CVal::LONGV);
      n->l = x;
      return n;
    }
  }
  c.error = true;
  return nullptr;
}

// top-level object -> record: per-field walk with a fresh depth budget
// (entities/admission.py unstructured_to_record); empty top records are
// kept (only NESTED empties drop)
CVal *adm_top_record(AdmCtx &c, const JVal *obj) {
  CVal *r = c.cp->make(CVal::RECV);
  std::vector<const JVal *> kids;
  dedupe_children(obj, kids);
  for (const JVal *kv : kids) {
    if (kv->kind == JVal::NUL) continue;
    CVal *val = adm_walk(c, 32, kv->key, kv);  // MAX_WALK_DEPTH
    if (c.error) return nullptr;
    if (!val) continue;
    r->fields.emplace_back(kv->key, val);
  }
  return r;
}

void canon_cval(const CVal *v, std::string &out);

// one canon construction per node per request: recursive calls route
// through the memoized canon_cval wrapper below, so nested sets/records
// cache too (CVal.canon / canon_done, cleared by CPool::make)
void canon_cval_build(const CVal *v, std::string &out) {
  switch (v->kind) {
    case CVal::STRV:
      canon_str_into(out, v->str);
      return;
    case CVal::LONGV: {
      char buf[24];
      int n = snprintf(buf, sizeof buf, "l%lld", (long long)v->l);
      out.append(buf, size_t(n));
      return;
    }
    case CVal::BOOLV:
      out.push_back(v->b ? 't' : 'f');
      return;
    case CVal::IPV: {
      // value_key tag "i": _canon() refuses it, so no vocab/set_has key
      // can ever hold one. The canon still NORMALIZES (canonical address
      // text — classify_ip only admits strict dotted-quad v4 and
      // ntop-round-trip v6 — plus the PARSED prefix length, defaulted to
      // the address family's max): the dyn eq tests byte-compare these
      // canons, and python IPAddr equality is (addr, prefixlen)
      sv s = v->str;
      sv a = s;
      long long plen = -1;
      size_t slash = s.rfind('/');
      if (slash != sv::npos) {
        a = s.substr(0, slash);
        py_int_parse(s.substr(slash + 1), &plen);  // valid per classify_ip
      }
      if (plen < 0) plen = a.find(':') != sv::npos ? 128 : 32;
      out.push_back('i');
      out.append(a.data(), a.size());
      char buf[8];
      int n = snprintf(buf, sizeof buf, "/%lld", plen);
      out.append(buf, size_t(n));
      return;
    }
    case CVal::ENTV:
      out.push_back('e');
      canon_len_prefix(out, v->ent_type.size());
      out.append(v->ent_type.data(), v->ent_type.size());
      canon_len_prefix(out, v->str.size());
      out.append(v->str.data(), v->str.size());
      return;
    case CVal::SETV: {
      std::vector<std::string> es;
      es.reserve(v->elems.size());
      for (const CVal *e : v->elems) {
        std::string ec;
        canon_cval(e, ec);
        es.push_back(std::move(ec));
      }
      canon_set_into(out, es);
      return;
    }
    case CVal::RECV: {
      std::vector<const std::pair<sv, CVal *> *> fs;
      fs.reserve(v->fields.size());
      for (const auto &f : v->fields) fs.push_back(&f);
      std::sort(fs.begin(), fs.end(),
                [](const auto *a, const auto *b) { return a->first < b->first; });
      out += "R{";
      for (size_t i = 0; i < fs.size(); ++i) {
        if (i) out.push_back('\x1f');
        canon_len_prefix(out, fs[i]->first.size());
        out.append(fs[i]->first.data(), fs[i]->first.size());
        out.push_back('\x1d');
        canon_cval(fs[i]->second, out);
      }
      out.push_back('}');
      return;
    }
  }
}

void canon_cval(const CVal *v, std::string &out) {
  if (!v->canon_done) {
    CVal *m = const_cast<CVal *>(v);  // pooled storage is never truly const
    m->canon.clear();
    canon_cval_build(v, m->canon);
    m->canon_done = true;
  }
  out += v->canon;
}

const CVal *cval_nav(const CVal *root, const std::vector<std::string> &comps) {
  // compiler/encode.py _slot_value: records only; anything else is MISSING
  const CVal *cur = root;
  for (const auto &comp : comps) {
    if (!cur || cur->kind != CVal::RECV) return nullptr;
    const CVal *nxt = nullptr;
    for (const auto &f : cur->fields)
      if (f.first == comp) nxt = f.second;
    cur = nxt;
    if (!cur) return nullptr;
  }
  return cur;
}

constexpr sv kAdmAction = "k8s::admission::Action";
constexpr sv kSkipNs1 = "kube-system";
constexpr sv kSkipNs2 = "cedar-k8s-authz-system";

struct AdmFeatures {
  sv uid, op, action_id;
  sv p_type, p_id;
  std::vector<sv> groups;
  CVal *p_rec = nullptr;
  std::string r_type;  // <group or core>::<kind version>::<Kind>
  std::string r_path;  // kubernetes URL path (the resource entity id)
  CVal *res = nullptr;
  CVal *ctx = nullptr;  // {oldObject: <old attrs>} on UPDATE-style requests

  void reset() {
    groups.clear();
    p_rec = res = ctx = nullptr;
    r_type.clear();
    r_path.clear();
    uid = op = action_id = p_type = p_id = sv();
  }
};

// request.kind / request.resource: python's known-field extraction
// ignores unknown keys and tolerates odd values (entities/admission.py
// from_admission_review), so this strict shape check is DELIBERATELY a
// superset — the rare flagged row answers through the Python fallback,
// which is the oracle; strictness here costs fallback speed, never parity
bool gv_shape_ok(const JVal *o, sv third_key) {
  if (!o || o->kind == JVal::NUL) return true;  // `or {}` -> defaults
  if (o->kind != JVal::OBJ) return false;
  for (const JVal *kv = o->child; kv; kv = kv->next) {
    if (kv->key != "group" && kv->key != "version" && kv->key != third_key)
      return false;
    if (kv->kind != JVal::STR) return false;
  }
  return true;
}

uint8_t build_adm(const JVal *root, AdmFeatures &f, AdmCtx &c, Arena &arena) {
  const JVal *req = root->get("request");
  if (!req || req->kind != JVal::OBJ) return F_ADM_ERROR;
  if (!str_if_present(req, "uid") || !str_if_present(req, "namespace") ||
      !str_if_present(req, "name") || !str_if_present(req, "subResource"))
    return F_ADM_ERROR;
  f.uid = str_field(req, "uid");
  if (f.uid.size() > 255) return F_ADM_ERROR;  // uid passback buffer bound
  // DEFERRED namespace skip: the decision is recorded here but only
  // returned after the FULL review validates — the reference decodes the
  // whole AdmissionReview into typed structs before Handle()'s namespace
  // check runs, so a malformed review in a skipped namespace must answer
  // through the conversion-error path (python allow-on-error), not the
  // skip. (Found by the type-flip fuzz: "userInfo": 7 in kube-system.)
  sv ns = str_field(req, "namespace");
  const bool ns_skip = (ns == kSkipNs1 || ns == kSkipNs2);
  f.op = str_field(req, "operation");
  if (f.op == "CREATE") f.action_id = "create";
  else if (f.op == "UPDATE") f.action_id = "update";
  else if (f.op == "DELETE") f.action_id = "delete";
  else if (f.op == "CONNECT") f.action_id = "connect";
  else return F_ADM_ERROR;  // python raises "unsupported operation"

  // ---- principal (entities/user.py user_to_cedar_entity; admission keeps
  // extra keys as-is — no convertExtra lower-casing on this path)
  const JVal *ui = req->get("userInfo");
  if (ui && ui->kind == JVal::NUL) ui = nullptr;  // `or {}`
  if (ui && ui->kind != JVal::OBJ) return F_ADM_ERROR;
  if (!str_if_present(ui, "username") || !str_if_present(ui, "uid"))
    return F_ADM_ERROR;
  sv uname = str_field(ui, "username");
  sv uuid = str_field(ui, "uid");
  f.p_type = kUser;
  sv p_name = uname;
  sv p_ns;
  if (starts_with(uname, "system:node:") && count_colons(uname) == 2) {
    f.p_type = kNode;
    p_name = uname.substr(strlen("system:node:"));
  }
  if (starts_with(uname, "system:serviceaccount:") && count_colons(uname) == 3) {
    f.p_type = kSA;
    size_t a = strlen("system:serviceaccount:");
    size_t b = uname.find(':', a);
    p_ns = uname.substr(a, b - a);
    p_name = uname.substr(b + 1);
  }
  f.p_id = uuid.empty() ? uname : uuid;
  const JVal *groups = ui ? ui->get("groups") : nullptr;
  if (groups && groups->kind != JVal::NUL) {
    if (groups->kind != JVal::ARR) return F_ADM_ERROR;
    for (const JVal *g = groups->child; g; g = g->next) {
      if (g->kind != JVal::STR) return F_ADM_ERROR;
      f.groups.push_back(g->str);
    }
  }
  f.p_rec = c.cp->make(CVal::RECV);
  {
    CVal *nm = c.cp->make(CVal::STRV);
    nm->str = p_name;
    if (!p_ns.empty()) {
      CVal *nsv = c.cp->make(CVal::STRV);
      nsv->str = p_ns;
      f.p_rec->fields.emplace_back("namespace", nsv);
    }
    f.p_rec->fields.emplace_back("name", nm);
    const JVal *extra = ui ? ui->get("extra") : nullptr;
    if (extra && extra->kind != JVal::NUL) {
      if (extra->kind != JVal::OBJ) return F_ADM_ERROR;
      if (extra->child) {
        CVal *set = c.cp->make(CVal::SETV);
        // duplicate extra keys: python's json.loads keeps only the last
        // value per key (dict), like every other object walk here
        std::vector<const JVal *> extra_kids;
        dedupe_children(extra, extra_kids);
        for (const JVal *kv : extra_kids) {
          if (kv->kind != JVal::ARR) return F_ADM_ERROR;
          CVal *vals = c.cp->make(CVal::SETV);
          for (const JVal *e = kv->child; e; e = e->next) {
            if (e->kind != JVal::STR) return F_ADM_ERROR;
            CVal *ev = c.cp->make(CVal::STRV);
            ev->str = e->str;
            vals->elems.push_back(ev);
          }
          CVal *r = c.cp->make(CVal::RECV);
          CVal *k = c.cp->make(CVal::STRV);
          k->str = kv->key;
          r->fields.emplace_back("key", k);
          r->fields.emplace_back("values", vals);
          set->elems.push_back(r);
        }
        f.p_rec->fields.emplace_back("extra", set);
      }
    }
  }

  // ---- resource entity type + id (entities/admission.py:207-224)
  const JVal *kind = req->get("kind");
  if (!gv_shape_ok(kind, "kind")) return F_ADM_ERROR;
  if (kind && kind->kind != JVal::OBJ) kind = nullptr;
  const JVal *gvr = req->get("resource");
  if (!gv_shape_ok(gvr, "resource")) return F_ADM_ERROR;
  if (gvr && gvr->kind != JVal::OBJ) gvr = nullptr;
  sv kver = str_field(kind, "version"), kkind = str_field(kind, "kind");
  sv rgroup = str_field(gvr, "group"), rver = str_field(gvr, "version");
  sv rres = str_field(gvr, "resource");
  sv name = str_field(req, "name"), subres = str_field(req, "subResource");
  sv egroup = rgroup.empty() ? sv("core") : rgroup;
  f.r_type.assign(egroup.data(), egroup.size());
  f.r_type += "::";
  f.r_type.append(kver.data(), kver.size());
  f.r_type += "::";
  f.r_type.append(kkind.data(), kkind.size());
  c.group = egroup;
  c.kversion = kver;
  c.kkind = kkind;
  std::string &p = f.r_path;
  if (rgroup.empty()) {
    p.assign("/api/");
  } else {
    p.assign("/apis/");
    p.append(rgroup.data(), rgroup.size());
    p.push_back('/');
  }
  p.append(rver.data(), rver.size());
  if (!ns.empty()) {
    p.append("/namespaces/");
    p.append(ns.data(), ns.size());
  }
  p.push_back('/');
  p.append(rres.data(), rres.size());
  if (!name.empty()) {
    p.push_back('/');
    p.append(name.data(), name.size());
  }
  if (!subres.empty()) {
    p.push_back('/');
    p.append(subres.data(), subres.size());
  }

  // ---- object walk (oldObject for DELETE, handler.go:95-99)
  bool obj_bad = false;
  auto load_obj = [&](const char *key) -> const JVal * {
    const JVal *o = req->get(key);
    if (!o || o->kind == JVal::NUL) return nullptr;
    if (o->kind == JVal::STR) {  // JSON-string payload: python json.loads
      JsonParser nested(o->str.data(), o->str.size(), arena);
      const JVal *parsed = nested.parse();
      if (!parsed) obj_bad = true;  // python raises -> allow-on-error
      return parsed;
    }
    return o;
  };
  const JVal *obj = load_obj("object");
  const JVal *oldo = load_obj("oldObject");
  if (obj_bad) return F_ADM_ERROR;
  if (ns_skip) {
    // deferred namespace skip fires HERE: everything above mirrors the
    // decode surface whose failures the Python lane answers with
    // allow-on-error (typed fields, nested JSON-string payloads); the
    // entity build below is handler-stage work the Python handler only
    // runs AFTER its own namespace check, and its failure modes
    // ("unstructured data is nil", unsupported walks) do not apply to
    // skipped rows
    return F_ADM_NS_SKIP;
  }
  const JVal *main_obj = (f.op == "DELETE") ? oldo : obj;
  if (!main_obj || main_obj->kind != JVal::OBJ)
    return F_ADM_ERROR;  // "unstructured data is nil" / non-object payload
  f.res = adm_top_record(c, main_obj);
  if (c.error) return F_ADM_ERROR;
  if (oldo && f.op != "DELETE") {
    if (oldo->kind != JVal::OBJ) return F_ADM_ERROR;
    CVal *old_rec = adm_top_record(c, oldo);
    if (c.error) return F_ADM_ERROR;
    // old entity re-IDed by the review uid; linked from the new object and
    // exposed as context.oldObject (handler.go:107-139)
    CVal *ent = c.cp->make(CVal::ENTV);
    ent->ent_type = sv(f.r_type);
    ent->str = f.uid;
    dedupe_insert(f.res->fields, "oldObject", ent);
    f.ctx = c.cp->make(CVal::RECV);
    if (old_rec) f.ctx->fields.emplace_back("oldObject", old_rec);
  }
  return F_OK;
}

void encode_adm_one(const Table &t, AdmFeatures &f, int32_t *codes,
                    ExtrasOut &extras, std::string &scratch) {
  for (int32_t i = 0; i < t.n_slots; ++i) codes[i] = 0;

  const sv types[3] = {f.p_type, kAdmAction, sv(f.r_type)};
  const sv ids[3] = {f.p_id, f.action_id, sv(f.r_path)};
  const char vtag[3] = {'0', '1', '2'};
  for (int v = 0; v < 3; ++v) {
    if (t.type_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      const int32_t *row = sv_find(t.type_map, scratch);
      codes[t.type_slot[v]] = row ? *row : 0;
    }
    if (t.uid_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      scratch.push_back('\x1f');
      scratch.append(ids[v].data(), ids[v].size());
      const int32_t *row = sv_find(t.uid_map, scratch);
      codes[t.uid_slot[v]] = row ? *row : 0;
    }
  }

  // principal ancestors: the group parents
  if (!t.anc_slots[0].empty() && !f.groups.empty()) {
    size_t filled = 0;
    const auto &slots = t.anc_slots[0];
    for (sv g : f.groups) {
      scratch.assign("0\x1f");
      scratch.append(kGroup.data(), kGroup.size());
      scratch.push_back('\x1f');
      scratch.append(g.data(), g.size());
      const auto *entry = sv_find(t.anc_map, scratch);
      if (!entry || entry->first == 0) continue;
      if (filled < slots.size()) {
        codes[slots[filled++]] = entry->first;
      } else {
        for (int32_t lid : entry->second) extras.push(lid);
      }
    }
  }
  // action ancestor: create/update/delete/connect all parent to "all"
  // (entities/admission.py admission_action_entities)
  if (!t.anc_slots[1].empty()) {
    scratch.assign("1\x1f");
    scratch.append(kAdmAction.data(), kAdmAction.size());
    scratch.append("\x1f" "all");
    const auto *entry = sv_find(t.anc_map, scratch);
    if (entry && entry->first != 0) codes[t.anc_slots[1][0]] = entry->first;
  }

  std::string vcanon;  // the slot value's canon: vocab key + dyn eq operand
  std::vector<std::string> ecs;  // SET slots: per-element canons, built ONCE
  for (const auto &s : t.slots) {
    const CVal *root = s.var == 0   ? f.p_rec
                       : s.var == 2 ? f.res
                       : s.var == 3 ? f.ctx
                                    : nullptr;
    const CVal *v = root ? cval_nav(root, s.comps) : nullptr;
    vcanon.clear();
    ecs.clear();
    const bool is_set = v && v->kind == CVal::SETV;
    const bool want_elems = is_set && (!s.dyns.empty() || !s.set_has.empty());
    if (want_elems) {
      // one element-canon pass serves all three consumers: the set's own
      // canon (canon_set_into — identical construction to canon_cval's
      // SETV branch, sorting + deduping ecs in place, which membership
      // probes below don't care about), the dyn tests, and the set_has
      // probes. The previous shape canonicalized every element up to
      // THREE times per slot — ~1.2us per labels/annotations entry on
      // the admission walk. (Element canons themselves are memoized on
      // the CVal nodes, so repeat visits copy cached strings.)
      ecs.reserve(v->elems.size());
      for (const CVal *e : v->elems) {
        std::string ec;
        canon_cval(e, ec);  // element canons memoized on the nodes
        ecs.push_back(std::move(ec));
      }
      if (v->canon_done) {
        // set-level canon already memoized (another slot visited this
        // node): reuse it — but STILL sort+dedupe ecs so the set_has /
        // dyn membership probes see exactly what the first-visit path
        // (canon_set_into) sees: a duplicated JSON element must push
        // each matching lit ONCE, in the same deterministic order, on
        // every visit and on the Python lane alike
        std::sort(ecs.begin(), ecs.end());
        ecs.erase(std::unique(ecs.begin(), ecs.end()), ecs.end());
        vcanon += v->canon;
      } else {
        canon_set_into(vcanon, ecs);
        CVal *m = const_cast<CVal *>(v);
        m->canon = vcanon;
        m->canon_done = true;
      }
    } else if (v) {
      // no per-element consumers: the memoized node canon covers sets too
      canon_cval(v, vcanon);
    }
    if (!s.dyns.empty()) {
      auto slot_canon = [&f](uint8_t var, const std::vector<std::string> &c,
                             std::string &out) {
        const CVal *sroot = var == 0   ? f.p_rec
                            : var == 2 ? f.res
                            : var == 3 ? f.ctx
                                       : nullptr;
        const CVal *sval = sroot ? cval_nav(sroot, c) : nullptr;
        if (!sval) return false;
        canon_cval(sval, out);
        return true;
      };
      eval_dyns(s, want_elems ? &ecs : nullptr, v ? &vcanon : nullptr,
                slot_canon, extras, scratch);
    }
    if (!v) continue;
    const int32_t *row = sv_find(s.vocab, vcanon);
    if (row) {
      codes[s.sidx] = *row;
    } else {
      codes[s.sidx] = s.present_row;
      if (v->kind == CVal::STRV) {
        for (const auto &lt : s.likes)
          if (like_match(lt.comps, v->str)) extras.push(lt.lit);
      } else if (v->kind == CVal::LONGV) {
        for (const auto &ct : s.cmps) {
          int64_t x = v->l;
          bool hit = ct.op == 0   ? x < ct.c
                     : ct.op == 1 ? x <= ct.c
                     : ct.op == 2 ? x > ct.c
                                  : x >= ct.c;
          if (hit) extras.push(ct.lit);
        }
      }
      if (!s.type_errs.empty()) {
        // mirror compiler/encode.value_tag over the CVal kinds
        uint8_t tag;
        switch (v->kind) {
          case CVal::STRV: tag = 's'; break;
          case CVal::LONGV: tag = 'l'; break;
          case CVal::BOOLV: tag = 'b'; break;
          case CVal::IPV: tag = 'i'; break;
          case CVal::SETV: tag = 'S'; break;
          case CVal::RECV: tag = 'R'; break;
          case CVal::ENTV: tag = 'e'; break;
          default: tag = '?'; break;
        }
        for (const auto &te : s.type_errs)
          if (te.want != tag) extras.push(te.lit);
      }
    }
    if (is_set && !s.set_has.empty()) {
      for (const auto &ec : ecs) {  // canons already built above
        const auto *lits = sv_find(s.set_has, ec);
        if (lits)
          for (int32_t lid : *lits) extras.push(lid);
      }
    }
  }
}

// Strict UTF-8 validation (RFC 3629, including overlong/surrogate/range
// rejection). The Python lane refuses most invalid UTF-8 (CPython's json
// decodes bytes with errors="surrogatepass": surrogate ENCODINGS are
// accepted there, everything else invalid raises), while this parser is
// byte-preserving — without this gate the same bytes could EVALUATE on
// the native lane and decode-error on the Python lane, making the
// decision depend on which lane a row takes. This gate is deliberately a
// superset of Python's rejection: flagged rows (including the surrogate
// class Python would accept) re-run through the Python fallback, which
// returns the Python lane's own verdict — parity holds either way. One
// pass over ~250-byte bodies: negligible next to the parse. (Found by
// the round-5 byte-mutation fuzz.)
bool utf8_valid(const uint8_t *p, size_t n) {
  size_t i = 0;
  while (i < n) {
    // ASCII fast path: 8 bytes per iteration while no high bit is set
    // (JSON bodies are overwhelmingly ASCII — this keeps the gate's cost
    // near one load per 8 bytes)
    while (i + 8 <= n) {
      uint64_t w;
      memcpy(&w, p + i, 8);
      if (w & 0x8080808080808080ull) break;
      i += 8;
    }
    if (i >= n) break;
    uint8_t b = p[i];
    if (b < 0x80) {
      ++i;
      continue;
    }
    size_t need;
    uint32_t cp;
    if ((b & 0xE0) == 0xC0) {
      need = 1;
      cp = b & 0x1Fu;
    } else if ((b & 0xF0) == 0xE0) {
      need = 2;
      cp = b & 0x0Fu;
    } else if ((b & 0xF8) == 0xF0) {
      need = 3;
      cp = b & 0x07u;
    } else {
      return false;  // continuation byte in lead position / 0xF8+
    }
    if (i + need >= n) return false;  // truncated sequence
    for (size_t k = 1; k <= need; ++k) {
      uint8_t c = p[i + k];
      if ((c & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (c & 0x3Fu);
    }
    if (need == 1 && cp < 0x80) return false;                    // overlong
    if (need == 2 && (cp < 0x800 || (cp - 0xD800u) < 0x800u)) return false;
    if (need == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    i += need + 1;
  }
  return true;
}

// One request's raw bytes, independent of how the batch arrived (packed
// buffer + offsets from ctypes, or per-item PyBytes pointers from the
// GIL-side harvest in the *_pylist entries).
struct ReqView {
  const uint8_t *p;
  uint64_t len;
};

// Persistent encode worker pool. The original drive_batch spawned fresh
// std::threads per batch — ~20-60us of clone/join overhead per call,
// which at serving chunk cadence (a few ms per 16k-row chunk, dozens of
// chunks/sec) ate a measurable slice of the encode budget and thrashed
// the scheduler. Workers here are created ONCE (growing to the largest
// thread count ever requested, capped), parked on a condition variable
// between batches, and handed (lo, hi) shard ranges through a shared
// cursor; the CALLING thread always runs one shard itself, so a pool of
// nt-1 workers serves an nt-way encode and a cold pool costs nothing on
// the first single-threaded call.
//
// Lifetime: the pool object is intentionally leaked (never destroyed).
// Workers blocked on the cv at process exit are reaped by _exit — unlike
// a joinable-thread destructor (std::terminate) or a pthread unwinding
// mid-C++-exception (the XLA warm-thread abort this codebase already
// guards against), a parked worker holds no lock and touches no state.
class EncodePool {
 public:
  static constexpr int kMaxWorkers = 64;

  // Run work(lo, hi) over [0, n) split into `shards` contiguous ranges,
  // the calling thread pulling shards alongside the pool workers. Blocks
  // until every shard completed. Thread-safe across concurrent callers
  // (each call owns a private Job; workers pull from the active job
  // queue). A busy or undersized pool degrades to the caller running
  // more shards itself — never to a deadlock or an unserved range.
  void run(uint64_t n, uint64_t shards,
           const std::function<void(uint64_t, uint64_t)> &work) {
    if (shards > n) shards = n;
    if (shards <= 1) {
      work(0, n);
      return;
    }
    ensure_workers(size_t(shards - 1));
    auto job = std::make_shared<Job>();
    job->work = &work;
    job->n = n;
    job->chunk = (n + shards - 1) / shards;
    job->next.store(0);
    {
      std::lock_guard<std::mutex> g(mu_);
      jobs_.push_back(job);
    }
    cv_work_.notify_all();
    while (run_one_shard(*job)) {
    }
    // every range is claimed INSIDE a pending window (run_one_shard
    // increments pending before touching the cursor), so pending == 0
    // with a drained cursor proves no shard — claimed or about to be
    // claimed — can still call `work` after this wait returns
    std::unique_lock<std::mutex> lk(job->mu);
    job->cv_done.wait(lk, [&] { return job->pending == 0 && job->drained; });
  }

 private:
  struct Job {
    const std::function<void(uint64_t, uint64_t)> *work;
    uint64_t n, chunk;
    std::atomic<uint64_t> next;
    std::mutex mu;
    std::condition_variable cv_done;
    int pending = 0;       // threads inside run_one_shard's claim window
    bool drained = false;  // cursor exhausted (job unlinked from queue)
  };

  // Claim + run the next range of `job`; false when the cursor is dry.
  // pending is raised BEFORE the cursor read: a thread holding a valid
  // range is always visible to run()'s completion wait (the gap between
  // fetch_add and a later increment would let run() return — and destroy
  // `work` — while this thread still intends to call it).
  bool run_one_shard(Job &job) {
    {
      std::lock_guard<std::mutex> g(job.mu);
      ++job.pending;
    }
    uint64_t lo = job.next.fetch_add(job.chunk);
    bool ran = lo < job.n;
    if (ran) {
      uint64_t hi = lo + job.chunk > job.n ? job.n : lo + job.chunk;
      (*job.work)(lo, hi);
    } else {
      unlink_job(job);
    }
    bool notify = false;
    {
      std::lock_guard<std::mutex> g(job.mu);
      --job.pending;
      notify = job.pending == 0 && job.drained;
    }
    if (notify) job.cv_done.notify_all();
    return ran;
  }

  void unlink_job(Job &job) {
    // first thread to see the dry cursor unlinks the job so workers stop
    // considering it (idempotent: late observers find nothing to erase)
    {
      std::lock_guard<std::mutex> g(mu_);
      for (size_t i = 0; i < jobs_.size(); ++i) {
        if (jobs_[i].get() == &job) {
          jobs_.erase(jobs_.begin() + i);
          break;
        }
      }
    }
    std::lock_guard<std::mutex> g(job.mu);
    job.drained = true;
  }

  void ensure_workers(size_t want) {
    if (want > kMaxWorkers) want = kMaxWorkers;
    std::lock_guard<std::mutex> g(mu_);
    while (n_workers_ < want) {
      std::thread([this] { worker_loop(); }).detach();
      ++n_workers_;
    }
  }

  void worker_loop() {
    for (;;) {
      std::shared_ptr<Job> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_work_.wait(lk, [&] { return !jobs_.empty(); });
        job = jobs_.front();  // shared_ptr copy: outlives run()'s return
      }
      // pull shards until this job's cursor runs dry; other queued jobs
      // are picked up on the next loop. A stale job (drained between the
      // copy and here) reads a dry cursor and never touches job->work.
      while (run_one_shard(*job)) {
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_work_;
  std::vector<std::shared_ptr<Job>> jobs_;
  size_t n_workers_ = 0;
};

EncodePool &encode_pool() {
  static EncodePool *pool = new EncodePool();  // leaked on purpose
  return *pool;
}

// Shared batch threading driver: split [0, n) into n_threads contiguous
// ranges (per-thread arenas/pools live inside `work`), executed on the
// persistent pool (the calling thread runs shards too).
template <class Work>
void drive_batch(uint64_t n, int32_t n_threads, Work &&work) {
  if (n_threads <= 1 || n < 64) {
    work(uint64_t(0), n);
    return;
  }
  const std::function<void(uint64_t, uint64_t)> fn = work;
  encode_pool().run(n, uint64_t(n_threads), fn);
}

// SAR encode over a request range. extras_pad >= 0 means the extras
// buffer arrived UNinitialized (np.empty): fill every row's unused cells
// up to extras_cap so outputs stay deterministic — batch results must be
// bit-identical regardless of entry point or thread count
// (tests/test_native_encoder.py pins this).
void encode_sar_rows(const Table &t, const ReqView *reqs, uint64_t lo,
                     uint64_t hi, int32_t *codes, int32_t *extras,
                     int32_t extras_cap, int32_t extras_pad,
                     int32_t *extras_count, uint8_t *flags) {
  Arena arena;
  Features f;
  std::string scratch;
  for (uint64_t i = lo; i < hi; ++i) {
    int32_t *c = codes + i * uint64_t(t.n_slots);
    ExtrasOut eo{extras + i * uint64_t(extras_cap), extras_cap};
    arena.reset();
    uint8_t flag;
    if (!reqs[i].p || !utf8_valid(reqs[i].p, size_t(reqs[i].len))) {
      // python-lane parity: invalid UTF-8 is a decode error, never an
      // evaluated request (see utf8_valid); a null view (non-bytes list
      // item) is likewise a decode error for the python lane to report
      flag = F_PARSE_ERROR;
    } else {
      JsonParser parser((const char *)reqs[i].p, size_t(reqs[i].len),
                        arena);
      JVal *root = parser.parse();
      if (!root || root->kind != JVal::OBJ) {
        flag = F_PARSE_ERROR;
      } else {
        f.reset();
        flag = build_features(root, f);
      }
    }
    if (flag != F_OK) {
      for (int32_t s = 0; s < t.n_slots; ++s) c[s] = 0;
      extras_count[i] = 0;
      flags[i] = flag;
    } else {
      encode_one(t, f, c, eo, scratch);
      extras_count[i] = eo.n;
      flags[i] = eo.overflow ? F_EXTRAS_OVERFLOW : F_OK;
    }
    if (extras_pad >= 0)
      for (int32_t k = eo.n; k < extras_cap; ++k) eo.buf[k] = extras_pad;
  }
}

// Admission encode over a request range (see ce_encode_adm_batch for the
// uids contract); extras_pad semantics as encode_sar_rows (fill EVERY
// row's unused cells: outputs stay deterministic across entry points).
void encode_adm_rows(const Table &t, const ReqView *reqs, uint64_t lo,
                     uint64_t hi, int32_t *codes, int32_t *extras,
                     int32_t extras_cap, int32_t extras_pad,
                     int32_t *extras_count, uint8_t *flags, char *uids,
                     int32_t *uid_lens) {
  Arena arena;
  CPool cpool;
  AdmFeatures f;
  std::string scratch;
  for (uint64_t i = lo; i < hi; ++i) {
    int32_t *c = codes + i * uint64_t(t.n_slots);
    ExtrasOut eo{extras + i * uint64_t(extras_cap), extras_cap};
    extras_count[i] = 0;
    uid_lens[i] = 0;
    arena.reset();
    cpool.reset();
    uint8_t flag = F_OK;
    if (!reqs[i].p || !utf8_valid(reqs[i].p, size_t(reqs[i].len))) {
      // python-lane parity: invalid UTF-8 is a decode error (utf8_valid);
      // null view (non-bytes list item) likewise
      flag = F_PARSE_ERROR;
    } else {
      JsonParser parser((const char *)reqs[i].p, size_t(reqs[i].len),
                        arena);
      JVal *root = parser.parse();
      if (!root || root->kind != JVal::OBJ) {
        flag = F_PARSE_ERROR;
      } else {
        f.reset();
        AdmCtx ctx;
        ctx.cp = &cpool;
        flag = build_adm(root, f, ctx, arena);
      }
    }
    if (flag != F_OK) {
      for (int32_t s = 0; s < t.n_slots; ++s) c[s] = 0;
      flags[i] = flag;
      if (flag == F_ADM_NS_SKIP) {
        memcpy(uids + i * 256, f.uid.data(), f.uid.size());
        uid_lens[i] = int32_t(f.uid.size());
      }
    } else {
      encode_adm_one(t, f, c, eo, scratch);
      extras_count[i] = eo.n;
      flags[i] = eo.overflow ? F_EXTRAS_OVERFLOW : F_OK;
      memcpy(uids + i * 256, f.uid.data(), f.uid.size());
      uid_lens[i] = int32_t(f.uid.size());
    }
    if (extras_pad >= 0)
      for (int32_t k = eo.n; k < extras_cap; ++k) eo.buf[k] = extras_pad;
  }
}

std::vector<ReqView> views_from_offsets(uint64_t n, const uint8_t *buf,
                                        const uint64_t *offsets,
                                        const uint64_t *lens) {
  std::vector<ReqView> reqs(n);
  for (uint64_t i = 0; i < n; ++i) reqs[i] = {buf + offsets[i], lens[i]};
  return reqs;
}

#ifdef CEDAR_PY_GLUE
// GIL-side harvest of a Python list of bytes-like objects into ReqViews.
// The Py_buffer views are HELD for the duration of the encode (release()
// under the GIL afterwards): an exported buffer pins bytearray /
// memoryview storage — resizing raises BufferError instead of
// invalidating the pointers the nogil worker threads are parsing. The
// caller-supplied `n` (the Python-side allocation size) caps the row
// count: a list mutated concurrently with the call can never overflow
// the caller's output arrays. Non-buffer items yield a null view ->
// F_PARSE_ERROR -> python fallback reports the exact decode error.
struct PyListViews {
  std::vector<ReqView> reqs;
  std::vector<Py_buffer> held;

  PyListViews(PyObject *list, uint64_t n_cap) {
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (uint64_t(n) > n_cap) n = Py_ssize_t(n_cap);
    reqs.resize(static_cast<size_t>(n), ReqView{nullptr, 0});
    held.reserve(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *o = PyList_GET_ITEM(list, i);  // borrowed
      Py_buffer vb;
      if (PyObject_GetBuffer(o, &vb, PyBUF_SIMPLE) != 0) {
        PyErr_Clear();
        continue;
      }
      reqs[size_t(i)] = {(const uint8_t *)vb.buf, uint64_t(vb.len)};
      held.push_back(vb);
    }
  }
  // GIL must be held
  void release() {
    for (auto &vb : held) PyBuffer_Release(&vb);
    held.clear();
  }
};
#endif  // CEDAR_PY_GLUE

}  // namespace

// ------------------------------------------------------------------ C API

extern "C" {

void *ce_load_table(const uint8_t *blob, uint64_t len) {
  return load_table(blob, size_t(len));
}

void ce_free_table(void *handle) { delete static_cast<Table *>(handle); }

// bodies are packed back to back in `buf`; request i spans
// [offsets[i], offsets[i] + lens[i]). codes: [n, n_slots] int32 (row
// indices); extras: [n, extras_cap] int32 pre-filled by the CALLER with the
// pad value; extras_count: [n] int32; flags: [n] uint8 (see F_* above).
void ce_encode_sar_batch(void *handle, uint64_t n, const uint8_t *buf,
                         const uint64_t *offsets, const uint64_t *lens,
                         int32_t *codes, int32_t *extras, int32_t extras_cap,
                         int32_t *extras_count, uint8_t *flags,
                         int32_t n_threads) {
  const Table &t = *static_cast<Table *>(handle);
  auto reqs = views_from_offsets(n, buf, offsets, lens);
  drive_batch(n, n_threads, [&](uint64_t lo, uint64_t hi) {
    encode_sar_rows(t, reqs.data(), lo, hi, codes, extras, extras_cap,
                    /*extras_pad=*/-1, extras_count, flags);
  });
}

int32_t ce_n_slots(void *handle) {
  return static_cast<Table *>(handle)->n_slots;
}

// AdmissionReview variant of ce_encode_sar_batch. Additional outputs: the
// review uid of each request is copied into uids[i * 256 .. ] (uid_lens[i]
// bytes) for F_OK / F_ADM_NS_SKIP rows so the caller can build responses
// without re-parsing; fallback rows (parse error / F_ADM_ERROR / overflow)
// re-run through the exact Python path instead.
void ce_encode_adm_batch(void *handle, uint64_t n, const uint8_t *buf,
                         const uint64_t *offsets, const uint64_t *lens,
                         int32_t *codes, int32_t *extras, int32_t extras_cap,
                         int32_t *extras_count, uint8_t *flags, char *uids,
                         int32_t *uid_lens, int32_t n_threads) {
  const Table &t = *static_cast<Table *>(handle);
  auto reqs = views_from_offsets(n, buf, offsets, lens);
  drive_batch(n, n_threads, [&](uint64_t lo, uint64_t hi) {
    encode_adm_rows(t, reqs.data(), lo, hi, codes, extras, extras_cap,
                    /*extras_pad=*/-1, extras_count, flags, uids, uid_lens);
  });
}

#ifdef CEDAR_PY_GLUE

// Python-list variants: called through a PyDLL view (GIL HELD on entry).
// The bodies list is harvested into pinned buffer views under the GIL,
// the GIL is released for the threaded encode, then the views release
// back under the GIL (see PyListViews for the lifetime argument).
// `n_alloc` is the caller's output-array row count — the hard cap on how
// many rows are encoded. `extras` arrives UNinitialized (np.empty);
// every row is pad-filled in C (extras_pad).
void ce_encode_sar_pylist(void *handle, PyObject *list, uint64_t n_alloc,
                          int32_t *codes, int32_t *extras,
                          int32_t extras_cap, int32_t extras_pad,
                          int32_t *extras_count, uint8_t *flags,
                          int32_t n_threads) {
  const Table &t = *static_cast<Table *>(handle);
  PyListViews views(list, n_alloc);
  uint64_t n = views.reqs.size();
  // if the list shrank concurrently, the trailing output rows would
  // otherwise stay np.empty garbage: make them deterministic error rows
  for (uint64_t i = n; i < n_alloc; ++i) {
    for (int32_t s = 0; s < t.n_slots; ++s) codes[i * t.n_slots + s] = 0;
    for (int32_t k = 0; k < extras_cap; ++k)
      extras[i * uint64_t(extras_cap) + k] = extras_pad;
    extras_count[i] = 0;
    flags[i] = F_PARSE_ERROR;
  }
  PyThreadState *st = PyEval_SaveThread();
  drive_batch(n, n_threads, [&](uint64_t lo, uint64_t hi) {
    encode_sar_rows(t, views.reqs.data(), lo, hi, codes, extras,
                    extras_cap, extras_pad, extras_count, flags);
  });
  PyEval_RestoreThread(st);
  views.release();
}

void ce_encode_adm_pylist(void *handle, PyObject *list, uint64_t n_alloc,
                          int32_t *codes, int32_t *extras,
                          int32_t extras_cap, int32_t extras_pad,
                          int32_t *extras_count, uint8_t *flags, char *uids,
                          int32_t *uid_lens, int32_t n_threads) {
  const Table &t = *static_cast<Table *>(handle);
  PyListViews views(list, n_alloc);
  uint64_t n = views.reqs.size();
  for (uint64_t i = n; i < n_alloc; ++i) {  // see SAR twin
    for (int32_t s = 0; s < t.n_slots; ++s) codes[i * t.n_slots + s] = 0;
    for (int32_t k = 0; k < extras_cap; ++k)
      extras[i * uint64_t(extras_cap) + k] = extras_pad;
    extras_count[i] = 0;
    uid_lens[i] = 0;
    flags[i] = F_PARSE_ERROR;
  }
  PyThreadState *st = PyEval_SaveThread();
  drive_batch(n, n_threads, [&](uint64_t lo, uint64_t hi) {
    encode_adm_rows(t, views.reqs.data(), lo, hi, codes, extras,
                    extras_cap, extras_pad, extras_count, flags, uids,
                    uid_lens);
  });
  PyEval_RestoreThread(st);
  views.release();
}

#endif  // CEDAR_PY_GLUE

}  // extern "C"
