// Native SAR fast path: raw SubjectAccessReview JSON -> feature codes.
//
// This is the TPU framework's host-side hot loop in C++: it fuses the work
// of the Python pipeline (server/http.py get_authorizer_attributes ->
// server/authorizer.py record_to_cedar_resource -> compiler/table.py
// encode_request_codes) into one pass over the raw request bytes, producing
// the [n_slots] dictionary-code vector + extras list the device kernel
// consumes. Behavior parity with the Python path is enforced by
// tests/test_native_encoder.py (randomized differential tests).
//
// Designed for allocation-free steady state: the JSON DOM is pointer-linked
// nodes bump-allocated from a reusable arena, string values are views into
// the request buffer (escaped strings — rare in SARs — are materialized
// into arena-owned storage), and hash-map probe keys are composed into
// reused scratch buffers.
//
// Reference behaviors mirrored (cites are to /root/reference):
//   * SAR -> attributes: internal/server/server.go:163-309
//   * principal typing + group parents: internal/server/entities/user.go:35
//   * action/resource/non-resource/impersonation entities:
//     internal/server/authorizer/entitiy_builders.go:13-143
//   * authorizer gates (self-allow, system:* skip):
//     internal/server/authorizer/authorizer.go:38-57
//
// The activation-table blob is serialized by cedar_tpu/native/__init__.py
// (format documented there); canonical value-key strings must stay in sync
// with _canon() on the Python side.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

using sv = std::string_view;

// ----------------------------------------------------------- tiny JSON DOM

struct JVal {
  enum Kind : uint8_t { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  sv str;        // STR payload
  sv key;        // member key when this node is an object member
  JVal *child = nullptr;  // first child (ARR/OBJ)
  JVal *next = nullptr;   // next sibling

  const JVal *get(sv k) const {
    if (kind != OBJ) return nullptr;
    // duplicate keys resolve to the last one, matching Python json.loads
    const JVal *found = nullptr;
    for (const JVal *c = child; c; c = c->next)
      if (c->key == k) found = c;
    return found;
  }
};

// Bump allocator with stable addresses, reusable across requests.
class Arena {
 public:
  JVal *alloc() {
    if (used_ == kChunk * chunks_.size()) chunks_.emplace_back(new JVal[kChunk]);
    JVal *v = &chunks_[used_ / kChunk][used_ % kChunk];
    ++used_;
    *v = JVal{};
    return v;
  }
  // arena-owned storage for escaped strings
  sv own(std::string &&s) {
    if (n_owned_ == owned_.size()) owned_.emplace_back();
    std::string &slot = owned_[n_owned_++];
    slot = std::move(s);
    return sv(slot);
  }
  void reset() {
    used_ = 0;
    n_owned_ = 0;
  }

 private:
  static constexpr size_t kChunk = 128;
  std::vector<std::unique_ptr<JVal[]>> chunks_;
  std::vector<std::string> owned_;
  size_t used_ = 0, n_owned_ = 0;
};

class JsonParser {
 public:
  JsonParser(const char *p, size_t n, Arena &arena)
      : p_(p), end_(p + n), arena_(arena) {}

  JVal *parse() {
    JVal *v = value();
    if (!v) return nullptr;
    ws();
    if (p_ != end_) return nullptr;  // trailing garbage
    return v;
  }

 private:
  const char *p_, *end_;
  Arena &arena_;

  void ws() {
    while (p_ < end_ && (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' || *p_ == '\r'))
      ++p_;
  }
  bool lit(const char *s, size_t n) {
    if (size_t(end_ - p_) < n || memcmp(p_, s, n) != 0) return false;
    p_ += n;
    return true;
  }

  JVal *value() {
    ws();
    if (p_ >= end_) return nullptr;
    switch (*p_) {
      case '{': return container(true);
      case '[': return container(false);
      case '"': {
        JVal *v = arena_.alloc();
        v->kind = JVal::STR;
        if (!string(v->str)) return nullptr;
        return v;
      }
      case 't': {
        if (!lit("true", 4)) return nullptr;
        JVal *v = arena_.alloc();
        v->kind = JVal::BOOL;
        v->b = true;
        return v;
      }
      case 'f': {
        if (!lit("false", 5)) return nullptr;
        JVal *v = arena_.alloc();
        v->kind = JVal::BOOL;
        return v;
      }
      case 'n': {
        if (!lit("null", 4)) return nullptr;
        return arena_.alloc();
      }
      default: return number();
    }
  }

  JVal *number() {
    if (p_ < end_ && *p_ == '-') ++p_;
    if (p_ >= end_ || *p_ < '0' || *p_ > '9') return nullptr;
    while (p_ < end_ && ((*p_ >= '0' && *p_ <= '9') || *p_ == '.' || *p_ == 'e' ||
                         *p_ == 'E' || *p_ == '+' || *p_ == '-'))
      ++p_;
    JVal *v = arena_.alloc();
    v->kind = JVal::NUM;
    return v;
  }

  static void utf8_append(std::string &out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back(char(cp));
    } else if (cp < 0x800) {
      out.push_back(char(0xC0 | (cp >> 6)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(char(0xE0 | (cp >> 12)));
      out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(char(0xF0 | (cp >> 18)));
      out.push_back(char(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(char(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t &out) {
    if (end_ - p_ < 4) return false;
    out = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p_++;
      out <<= 4;
      if (c >= '0' && c <= '9') out |= uint32_t(c - '0');
      else if (c >= 'a' && c <= 'f') out |= uint32_t(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') out |= uint32_t(c - 'A' + 10);
      else return false;
    }
    return true;
  }

  // Fast path: no escapes -> a view into the input buffer, zero copies.
  bool string(sv &out) {
    ++p_;  // opening quote
    const char *start = p_;
    while (p_ < end_ && *p_ != '"' && *p_ != '\\') ++p_;
    if (p_ >= end_) return false;
    if (*p_ == '"') {
      out = sv(start, size_t(p_ - start));
      ++p_;
      return true;
    }
    // slow path: materialize with escape processing
    std::string buf(start, size_t(p_ - start));
    while (p_ < end_) {
      char c = *p_;
      if (c == '"') {
        ++p_;
        out = arena_.own(std::move(buf));
        return true;
      }
      if (c == '\\') {
        ++p_;
        if (p_ >= end_) return false;
        char e = *p_++;
        switch (e) {
          case '"': buf.push_back('"'); break;
          case '\\': buf.push_back('\\'); break;
          case '/': buf.push_back('/'); break;
          case 'b': buf.push_back('\b'); break;
          case 'f': buf.push_back('\f'); break;
          case 'n': buf.push_back('\n'); break;
          case 'r': buf.push_back('\r'); break;
          case 't': buf.push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!hex4(cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF && end_ - p_ >= 6 && p_[0] == '\\' &&
                p_[1] == 'u') {
              const char *save = p_;
              p_ += 2;
              uint32_t lo;
              if (!hex4(lo)) return false;
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              } else {
                p_ = save;  // lone high surrogate; encode as-is (WTF-8)
              }
            }
            utf8_append(buf, cp);
            break;
          }
          default: return false;
        }
      } else {
        buf.push_back(c);
        ++p_;
      }
    }
    return false;  // unterminated
  }

  // Nesting cap: a hostile body of 1MB of '[' would otherwise recurse once
  // per byte and overflow the native stack (no RecursionError here — the
  // whole webhook process would segfault). Beyond the cap the parse fails,
  // the row gets F_PARSE_ERROR, and the caller falls back to the Python
  // path, whose json.loads raises a handled RecursionError.
  static constexpr int kMaxDepth = 256;
  int depth_ = 0;

  JVal *container(bool is_obj) {
    if (depth_ >= kMaxDepth) return nullptr;
    ++depth_;
    JVal *v = container_body(is_obj);
    --depth_;
    return v;
  }

  JVal *container_body(bool is_obj) {
    ++p_;  // '{' or '['
    JVal *v = arena_.alloc();
    v->kind = is_obj ? JVal::OBJ : JVal::ARR;
    char close = is_obj ? '}' : ']';
    ws();
    if (p_ < end_ && *p_ == close) {
      ++p_;
      return v;
    }
    JVal *tail = nullptr;
    while (true) {
      sv key;
      if (is_obj) {
        ws();
        if (p_ >= end_ || *p_ != '"' || !string(key)) return nullptr;
        ws();
        if (p_ >= end_ || *p_ != ':') return nullptr;
        ++p_;
      }
      JVal *mv = value();
      if (!mv) return nullptr;
      mv->key = key;
      if (tail) tail->next = mv;
      else v->child = mv;
      tail = mv;
      ws();
      if (p_ < end_ && *p_ == ',') {
        ++p_;
        continue;
      }
      if (p_ < end_ && *p_ == close) {
        ++p_;
        return v;
      }
      return nullptr;
    }
  }
};

// --------------------------------------------------------- encoder tables

struct LikeComp {
  bool wild;
  std::string s;
};

struct LikeTest {
  int32_t lit;
  std::vector<LikeComp> comps;
};

struct CmpTest {
  int32_t lit;
  uint8_t op;  // 0 '<', 1 '<=', 2 '>', 3 '>='
  int64_t c;
};

// string hash usable for string_view probes without key construction
struct SvHash {
  using is_transparent = void;
  size_t operator()(sv s) const { return std::hash<sv>{}(s); }
  size_t operator()(const std::string &s) const { return std::hash<sv>{}(s); }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(sv a, sv b) const { return a == b; }
};

template <class V>
using SvMap = std::unordered_map<std::string, V, SvHash, SvEq>;

template <class V>
const V *sv_find(const SvMap<V> &m, sv key) {
#if defined(__cpp_lib_generic_unordered_lookup) && \
    __cpp_lib_generic_unordered_lookup >= 201811L
  auto it = m.find(key);
#else
  thread_local std::string scratch;
  scratch.assign(key.data(), key.size());
  auto it = m.find(scratch);
#endif
  return it == m.end() ? nullptr : &it->second;
}

struct ScalarSlot {
  uint8_t var;       // 0 principal, 1 action, 2 resource, 3 context/other
  bool deep;         // multi-component path => value always missing (authz)
  std::string attr;  // single-component attribute path
  int32_t sidx;
  int32_t present_row;
  SvMap<int32_t> vocab;  // canon(value) -> row
  std::vector<LikeTest> likes;
  std::vector<CmpTest> cmps;
  SvMap<std::vector<int32_t>> set_has;
};

struct Table {
  int32_t n_slots = 0;
  int32_t type_slot[3] = {-1, -1, -1};
  int32_t uid_slot[3] = {-1, -1, -1};
  std::vector<int32_t> anc_slots[3];
  SvMap<int32_t> type_map;  // v \x1f type
  SvMap<int32_t> uid_map;   // v \x1f type \x1f id
  SvMap<std::pair<int32_t, std::vector<int32_t>>> anc_map;
  std::vector<ScalarSlot> slots;
};

class BlobReader {
 public:
  BlobReader(const uint8_t *p, size_t n) : p_(p), end_(p + n) {}
  bool ok() const { return ok_; }

  uint8_t u8() { return ok_ && p_ < end_ ? *p_++ : (ok_ = false, 0); }
  int32_t i32() {
    if (!ok_ || end_ - p_ < 4) return ok_ = false, 0;
    int32_t v;
    memcpy(&v, p_, 4);
    p_ += 4;
    return v;
  }
  int64_t i64() {
    if (!ok_ || end_ - p_ < 8) return ok_ = false, 0;
    int64_t v;
    memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  std::string str() {
    int32_t n = i32();
    if (!ok_ || n < 0 || end_ - p_ < n) return ok_ = false, std::string();
    std::string s((const char *)p_, size_t(n));
    p_ += n;
    return s;
  }

 private:
  const uint8_t *p_, *end_;
  bool ok_ = true;
};

Table *load_table(const uint8_t *blob, size_t len) {
  BlobReader r(blob, len);
  if (r.i32() != 0x43544231) return nullptr;  // "CTB1"
  auto t = std::make_unique<Table>();
  t->n_slots = r.i32();
  for (int v = 0; v < 3; ++v) {
    t->type_slot[v] = r.i32();
    t->uid_slot[v] = r.i32();
    int32_t n = r.i32();
    for (int32_t i = 0; i < n; ++i) t->anc_slots[v].push_back(r.i32());
  }
  int32_t n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    t->type_map[std::move(k)] = r.i32();
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    t->uid_map[std::move(k)] = r.i32();
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    std::string k = r.str();
    int32_t row = r.i32();
    int32_t nl = r.i32();
    std::vector<int32_t> lits(size_t(nl >= 0 ? nl : 0));
    for (auto &l : lits) l = r.i32();
    t->anc_map[std::move(k)] = {row, std::move(lits)};
  }
  n = r.i32();
  for (int32_t i = 0; i < n; ++i) {
    ScalarSlot s;
    s.var = r.u8();
    s.deep = r.u8() != 0;
    s.attr = r.str();
    s.sidx = r.i32();
    s.present_row = r.i32();
    int32_t nv = r.i32();
    for (int32_t j = 0; j < nv; ++j) {
      std::string k = r.str();
      s.vocab[std::move(k)] = r.i32();
    }
    int32_t nl = r.i32();
    for (int32_t j = 0; j < nl; ++j) {
      LikeTest lt;
      lt.lit = r.i32();
      int32_t nc = r.i32();
      for (int32_t c = 0; c < nc; ++c) {
        LikeComp comp;
        comp.wild = r.u8() != 0;
        if (!comp.wild) comp.s = r.str();
        lt.comps.push_back(std::move(comp));
      }
      s.likes.push_back(std::move(lt));
    }
    int32_t ncmp = r.i32();
    for (int32_t j = 0; j < ncmp; ++j) {
      CmpTest c;
      c.lit = r.i32();
      c.op = r.u8();
      c.c = r.i64();
      s.cmps.push_back(c);
    }
    int32_t nsh = r.i32();
    for (int32_t j = 0; j < nsh; ++j) {
      std::string k = r.str();
      int32_t cnt = r.i32();
      std::vector<int32_t> lits(size_t(cnt >= 0 ? cnt : 0));
      for (auto &l : lits) l = r.i32();
      s.set_has[std::move(k)] = std::move(lits);
    }
    t->slots.push_back(std::move(s));
  }
  if (!r.ok()) return nullptr;
  return t.release();
}

// ------------------------------------------------------- like-glob matcher

// Mirrors cedar_tpu/lang/ast.py _match_components: DP over (component,
// position); components are literal chunks and wildcards.
bool like_match(const std::vector<LikeComp> &comps, sv s) {
  size_t n = s.size();
  thread_local std::vector<uint8_t> cur, next;
  cur.assign(n + 1, 0);
  next.assign(n + 1, 0);
  cur[0] = 1;
  for (const auto &comp : comps) {
    std::fill(next.begin(), next.end(), 0);
    if (comp.wild) {
      // wildcard: any reachable position reaches all later positions
      uint8_t reach = 0;
      for (size_t i = 0; i <= n; ++i) {
        reach |= cur[i];
        next[i] = reach;
      }
    } else {
      size_t m = comp.s.size();
      for (size_t i = 0; i + m <= n; ++i)
        if (cur[i] && memcmp(s.data() + i, comp.s.data(), m) == 0)
          next[i + m] = 1;
    }
    std::swap(cur, next);
  }
  return cur[n] != 0;
}

// --------------------------------------------------- canonical value keys

// Must stay byte-identical with _canon() in cedar_tpu/native/__init__.py.
void canon_str_into(std::string &out, sv s) {
  out.push_back('s');
  out.append(s.data(), s.size());
}

void canon_set_into(std::string &out, std::vector<std::string> &elems) {
  std::sort(elems.begin(), elems.end());
  out += "S{";
  for (size_t i = 0; i < elems.size(); ++i) {
    if (i) out.push_back('\x1f');
    out += elems[i];
  }
  out.push_back('}');
}

// record with keys pre-sorted by the caller
std::string canon_record(
    std::initializer_list<std::pair<const char *, const std::string *>> fields) {
  std::string out = "R{";
  bool first = true;
  for (const auto &f : fields) {
    if (!first) out.push_back('\x1f');
    first = false;
    out += f.first;
    out.push_back('\x1d');
    out += *f.second;
  }
  out.push_back('}');
  return out;
}

// -------------------------------------------------------- request features

// A slot value: authz-domain values are strings or sets-of-records.
struct Value {
  enum Kind { MISSING, STRV, SETV } kind = MISSING;
  sv str;
  std::vector<std::string> *elems = nullptr;  // element canon strings
};

struct Features {
  // principal
  sv p_type, p_id;
  std::vector<std::pair<sv, sv>> p_attrs;  // name / namespace
  std::vector<sv> groups;
  std::vector<std::string> extra_elem_canons;
  bool has_extra = false;
  // action
  sv verb;
  // resource entity
  sv r_type, r_id;
  std::vector<std::pair<sv, sv>> r_attrs;
  std::vector<std::string> label_elem_canons, field_elem_canons;
  bool has_label = false, has_field = false;
  // owned storage for composed strings (SA ids, resource paths, lowered keys)
  std::string own0, own1;

  void reset() {
    p_attrs.clear();
    groups.clear();
    extra_elem_canons.clear();
    has_extra = false;
    r_attrs.clear();
    label_elem_canons.clear();
    field_elem_canons.clear();
    has_label = has_field = false;
    own0.clear();
    own1.clear();
    p_type = p_id = verb = r_type = r_id = sv();
  }
};

constexpr sv kUser = "k8s::User";
constexpr sv kGroup = "k8s::Group";
constexpr sv kSA = "k8s::ServiceAccount";
constexpr sv kNode = "k8s::Node";
constexpr sv kPrincipalUID = "k8s::PrincipalUID";
constexpr sv kExtra = "k8s::Extra";
constexpr sv kResource = "k8s::Resource";
constexpr sv kNonResource = "k8s::NonResourceURL";
constexpr sv kAction = "k8s::Action";

int count_colons(sv s) {
  int n = 0;
  for (char c : s)
    if (c == ':') ++n;
  return n;
}

bool starts_with(sv s, sv prefix) {
  return s.size() >= prefix.size() && s.compare(0, prefix.size(), prefix) == 0;
}

sv str_field(const JVal *o, sv k) {
  const JVal *v = o ? o->get(k) : nullptr;
  return v && v->kind == JVal::STR ? v->str : sv();
}

// flags returned per request
enum : uint8_t {
  F_OK = 0,
  F_PARSE_ERROR = 1,
  F_SELF_ALLOW_POLICIES = 2,
  F_SELF_ALLOW_RBAC = 3,
  F_SYSTEM_SKIP = 4,
  F_EXTRAS_OVERFLOW = 5,
};

constexpr sv kAuthorizerIdentity = "system:authorizer:cedar-authorizer";

bool is_read_only(sv verb) {
  return verb == "get" || verb == "list" || verb == "watch";
}

// Build all request features from the parsed SAR. Returns a gate flag or
// F_OK. Mirrors get_authorizer_attributes + record_to_cedar_resource.
uint8_t build_features(const JVal *root, Features &f) {
  const JVal *spec = root->get("spec");
  if (spec && spec->kind != JVal::OBJ) spec = nullptr;

  sv user_name = str_field(spec, "user");
  sv user_uid = str_field(spec, "uid");

  const JVal *ra = spec ? spec->get("resourceAttributes") : nullptr;
  if (ra && ra->kind != JVal::OBJ) ra = nullptr;
  const JVal *nra = spec ? spec->get("nonResourceAttributes") : nullptr;
  if (nra && nra->kind != JVal::OBJ) nra = nullptr;

  sv verb, ns, group, version, resource, subresource, name, path;
  bool resource_request = false;
  if (ra) {
    verb = str_field(ra, "verb");
    ns = str_field(ra, "namespace");
    group = str_field(ra, "group");
    version = str_field(ra, "version");
    resource = str_field(ra, "resource");
    subresource = str_field(ra, "subresource");
    name = str_field(ra, "name");
    resource_request = true;
  }
  if (nra) {  // nonResourceAttributes wins last, like the Python builder
    path = str_field(nra, "path");
    verb = str_field(nra, "verb");
    resource_request = false;
  }

  // ------- authorizer gates (authorizer.go:38-57)
  if (user_name == kAuthorizerIdentity && is_read_only(verb)) {
    if (group == "cedar.k8s.aws" && resource == "policies")
      return F_SELF_ALLOW_POLICIES;
    if (group == "rbac.authorization.k8s.io") return F_SELF_ALLOW_RBAC;
  }
  if (starts_with(user_name, "system:") &&
      !starts_with(user_name, "system:serviceaccount:") &&
      !starts_with(user_name, "system:node:"))
    return F_SYSTEM_SKIP;

  // ------- principal (user.go:35)
  f.p_type = kUser;
  sv p_name = user_name;
  if (starts_with(user_name, "system:node:") && count_colons(user_name) == 2) {
    f.p_type = kNode;
    p_name = user_name.substr(strlen("system:node:"));
  }
  if (starts_with(user_name, "system:serviceaccount:") &&
      count_colons(user_name) == 3) {
    f.p_type = kSA;
    size_t a = strlen("system:serviceaccount:");
    size_t b = user_name.find(':', a);
    f.p_attrs.emplace_back("namespace", user_name.substr(a, b - a));
    p_name = user_name.substr(b + 1);
  }
  f.p_attrs.emplace_back("name", p_name);
  f.p_id = user_uid.empty() ? user_name : user_uid;

  const JVal *groups = spec ? spec->get("groups") : nullptr;
  if (groups && groups->kind == JVal::ARR)
    for (const JVal *g = groups->child; g; g = g->next)
      if (g->kind == JVal::STR) f.groups.push_back(g->str);

  const JVal *extra = spec ? spec->get("extra") : nullptr;
  if (extra && extra->kind == JVal::OBJ && extra->child) {
    f.has_extra = true;
    for (const JVal *kv = extra->child; kv; kv = kv->next) {
      // convertExtra lower-cases keys (server.go:205)
      std::string key = "s";
      key.reserve(kv->key.size() + 1);
      for (char c : kv->key)
        key.push_back(c >= 'A' && c <= 'Z' ? char(c + 32) : c);
      std::vector<std::string> vals;
      if (kv->kind == JVal::ARR)
        for (const JVal *v = kv->child; v; v = v->next)
          if (v->kind == JVal::STR) {
            std::string c;
            canon_str_into(c, v->str);
            vals.push_back(std::move(c));
          }
      std::string vset;
      canon_set_into(vset, vals);
      f.extra_elem_canons.push_back(
          canon_record({{"key", &key}, {"values", &vset}}));
    }
  }

  f.verb = verb;

  // ------- resource entity (entitiy_builders.go)
  if (resource_request && verb == "impersonate") {
    if (resource == "serviceaccounts") {
      f.r_type = kSA;
      f.own0.assign("system:serviceaccount:");
      f.own0.append(ns.data(), ns.size());
      f.own0.push_back(':');
      f.own0.append(name.data(), name.size());
      f.r_id = f.own0;
      f.r_attrs.emplace_back("name", name);
      f.r_attrs.emplace_back("namespace", ns);
    } else if (resource == "uids") {
      f.r_type = kPrincipalUID;
      f.r_id = name;
    } else if (resource == "users") {
      f.r_type = kUser;
      sv rname = name;
      if (starts_with(name, "system:node:") && count_colons(name) == 2) {
        f.r_type = kNode;
        rname = name.substr(strlen("system:node:"));
      }
      f.r_attrs.emplace_back("name", rname);
      f.r_id = name;
    } else if (resource == "groups") {
      f.r_type = kGroup;
      f.r_id = name;
      f.r_attrs.emplace_back("name", name);
    } else if (resource == "userextras") {
      f.r_type = kExtra;
      f.r_id = subresource;
      f.r_attrs.emplace_back("key", subresource);
      if (!name.empty()) f.r_attrs.emplace_back("value", name);
    } else {
      f.r_type = sv();
      f.r_id = sv();
    }
  } else if (resource_request) {
    f.r_type = kResource;
    std::string &p = f.own0;
    if (group.empty()) {
      p.assign("/api/");
    } else {
      p.assign("/apis/");
      p.append(group.data(), group.size());
      p.push_back('/');
    }
    p.append(version.data(), version.size());
    if (!ns.empty()) {
      p.append("/namespaces/");
      p.append(ns.data(), ns.size());
    }
    p.push_back('/');
    p.append(resource.data(), resource.size());
    if (!name.empty()) {
      p.push_back('/');
      p.append(name.data(), name.size());
    }
    if (!subresource.empty()) {
      p.push_back('/');
      p.append(subresource.data(), subresource.size());
    }
    f.r_id = p;
    f.r_attrs.emplace_back("apiGroup", group);
    f.r_attrs.emplace_back("resource", resource);
    if (!name.empty()) f.r_attrs.emplace_back("name", name);
    if (!subresource.empty()) f.r_attrs.emplace_back("subresource", subresource);
    if (!ns.empty()) f.r_attrs.emplace_back("namespace", ns);

    // selectors (server.go:221-309)
    const JVal *ls = ra->get("labelSelector");
    const JVal *reqs =
        ls && ls->kind == JVal::OBJ ? ls->get("requirements") : nullptr;
    if (reqs && reqs->kind == JVal::ARR && reqs->child) {
      for (const JVal *rq = reqs->child; rq; rq = rq->next) {
        if (rq->kind != JVal::OBJ) continue;
        sv op = str_field(rq, "operator");
        const char *mapped = nullptr;
        if (op == "In") mapped = "in";
        else if (op == "NotIn") mapped = "notin";
        else if (op == "Exists") mapped = "exists";
        else if (op == "DoesNotExist") mapped = "!";
        if (!mapped) continue;  // invalid operators dropped
        std::vector<std::string> vals;
        const JVal *vv = rq->get("values");
        if (vv && vv->kind == JVal::ARR)
          for (const JVal *v = vv->child; v; v = v->next)
            if (v->kind == JVal::STR) {
              std::string c;
              canon_str_into(c, v->str);
              vals.push_back(std::move(c));
            }
        std::string key, ops, vset;
        canon_str_into(key, str_field(rq, "key"));
        canon_str_into(ops, mapped);
        canon_set_into(vset, vals);
        f.label_elem_canons.push_back(canon_record(
            {{"key", &key}, {"operator", &ops}, {"values", &vset}}));
      }
      f.has_label = !f.label_elem_canons.empty();
    }
    const JVal *fs = ra->get("fieldSelector");
    const JVal *freqs =
        fs && fs->kind == JVal::OBJ ? fs->get("requirements") : nullptr;
    if (freqs && freqs->kind == JVal::ARR && freqs->child) {
      for (const JVal *rq = freqs->child; rq; rq = rq->next) {
        if (rq->kind != JVal::OBJ) continue;
        sv op = str_field(rq, "operator");
        const JVal *vv = rq->get("values");
        size_t nvals = 0;
        const JVal *first_val = nullptr;
        if (vv && vv->kind == JVal::ARR)
          for (const JVal *v = vv->child; v; v = v->next) {
            if (!first_val) first_val = v;
            ++nvals;
          }
        const char *mapped = nullptr;
        if (op == "In" && nvals == 1) mapped = "=";
        else if (op == "NotIn" && nvals == 1) mapped = "!=";
        if (!mapped) continue;
        sv val = first_val && first_val->kind == JVal::STR ? first_val->str : sv();
        std::string fld, ops, vc;
        canon_str_into(fld, str_field(rq, "key"));
        canon_str_into(ops, mapped);
        canon_str_into(vc, val);
        f.field_elem_canons.push_back(canon_record(
            {{"field", &fld}, {"operator", &ops}, {"value", &vc}}));
      }
      f.has_field = !f.field_elem_canons.empty();
    }
  } else {
    f.r_type = kNonResource;
    f.r_id = path;
    f.r_attrs.emplace_back("path", path);
  }
  return F_OK;
}

// ------------------------------------------------------------ slot lookup

struct ExtrasOut {
  int32_t *buf;
  int32_t cap;
  int32_t n = 0;
  bool overflow = false;
  void push(int32_t v) {
    if (n < cap) buf[n++] = v;
    else overflow = true;
  }
};

Value slot_value(Features &f, const ScalarSlot &s) {
  Value v;
  if (s.deep || s.var == 3) return v;  // context is empty for authz; deep
                                       // paths never resolve in this domain
  if (s.var == 0) {  // principal
    for (const auto &kv : f.p_attrs)
      if (kv.first == s.attr) {
        v.kind = Value::STRV;
        v.str = kv.second;
        return v;
      }
    if (s.attr == "extra" && f.has_extra) {
      v.kind = Value::SETV;
      v.elems = &f.extra_elem_canons;
    }
    return v;
  }
  if (s.var == 1) return v;  // action entities carry no attributes
  // resource
  for (const auto &kv : f.r_attrs)
    if (kv.first == s.attr) {
      v.kind = Value::STRV;
      v.str = kv.second;
      return v;
    }
  if (s.attr == "labelSelector" && f.has_label) {
    v.kind = Value::SETV;
    v.elems = &f.label_elem_canons;
  } else if (s.attr == "fieldSelector" && f.has_field) {
    v.kind = Value::SETV;
    v.elems = &f.field_elem_canons;
  }
  return v;
}

void encode_one(const Table &t, Features &f, int32_t *codes, ExtrasOut &extras,
                std::string &scratch) {
  for (int32_t i = 0; i < t.n_slots; ++i) codes[i] = 0;

  const sv types[3] = {f.p_type, kAction, f.r_type};
  const sv ids[3] = {f.p_id, f.verb, f.r_id};

  const char vtag[3] = {'0', '1', '2'};
  for (int v = 0; v < 3; ++v) {
    if (t.type_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      const int32_t *row = sv_find(t.type_map, scratch);
      codes[t.type_slot[v]] = row ? *row : 0;
    }
    if (t.uid_slot[v] >= 0) {
      scratch.clear();
      scratch.push_back(vtag[v]);
      scratch.push_back('\x1f');
      scratch.append(types[v].data(), types[v].size());
      scratch.push_back('\x1f');
      scratch.append(ids[v].data(), ids[v].size());
      const int32_t *row = sv_find(t.uid_map, scratch);
      codes[t.uid_slot[v]] = row ? *row : 0;
    }
  }

  // principal ancestors: group parent entities (user.go:23-27). Actions and
  // resources have no parents in the authz domain.
  if (!t.anc_slots[0].empty() && !f.groups.empty()) {
    size_t filled = 0;
    const auto &slots = t.anc_slots[0];
    for (sv g : f.groups) {
      scratch.assign("0\x1f");
      scratch.append(kGroup.data(), kGroup.size());
      scratch.push_back('\x1f');
      scratch.append(g.data(), g.size());
      const auto *entry = sv_find(t.anc_map, scratch);
      if (!entry || entry->first == 0) continue;
      if (filled < slots.size()) {
        codes[slots[filled++]] = entry->first;
      } else {
        for (int32_t lid : entry->second) extras.push(lid);
      }
    }
  }

  for (const auto &s : t.slots) {
    Value v = slot_value(f, s);
    if (v.kind == Value::MISSING) continue;

    scratch.clear();
    if (v.kind == Value::STRV) {
      canon_str_into(scratch, v.str);
    } else {
      canon_set_into(scratch, *v.elems);  // sorts elems in place (stable key)
    }
    const int32_t *row = sv_find(s.vocab, scratch);
    if (row) {
      codes[s.sidx] = *row;
    } else {
      codes[s.sidx] = s.present_row;
      if (v.kind == Value::STRV) {
        for (const auto &lt : s.likes)
          if (like_match(lt.comps, v.str)) extras.push(lt.lit);
        // cmp tests only apply to longs; authz values are strings
      }
    }
    if (v.kind == Value::SETV && !s.set_has.empty()) {
      for (const auto &ec : *v.elems) {
        const auto *lits = sv_find(s.set_has, ec);
        if (lits)
          for (int32_t lid : *lits) extras.push(lid);
      }
    }
  }
}

}  // namespace

// ------------------------------------------------------------------ C API

extern "C" {

void *ce_load_table(const uint8_t *blob, uint64_t len) {
  return load_table(blob, size_t(len));
}

void ce_free_table(void *handle) { delete static_cast<Table *>(handle); }

// bodies are packed back to back in `buf`; request i spans
// [offsets[i], offsets[i] + lens[i]). codes: [n, n_slots] int32 (row
// indices); extras: [n, extras_cap] int32 pre-filled by the CALLER with the
// pad value; extras_count: [n] int32; flags: [n] uint8 (see F_* above).
void ce_encode_sar_batch(void *handle, uint64_t n, const uint8_t *buf,
                         const uint64_t *offsets, const uint64_t *lens,
                         int32_t *codes, int32_t *extras, int32_t extras_cap,
                         int32_t *extras_count, uint8_t *flags,
                         int32_t n_threads) {
  const Table &t = *static_cast<Table *>(handle);
  auto work = [&](uint64_t lo, uint64_t hi) {
    Arena arena;
    Features f;
    std::string scratch;
    for (uint64_t i = lo; i < hi; ++i) {
      int32_t *c = codes + i * uint64_t(t.n_slots);
      ExtrasOut eo{extras + i * uint64_t(extras_cap), extras_cap};
      arena.reset();
      JsonParser parser((const char *)buf + offsets[i], size_t(lens[i]), arena);
      JVal *root = parser.parse();
      if (!root || root->kind != JVal::OBJ) {
        for (int32_t s = 0; s < t.n_slots; ++s) c[s] = 0;
        extras_count[i] = 0;
        flags[i] = F_PARSE_ERROR;
        continue;
      }
      f.reset();
      uint8_t gate = build_features(root, f);
      if (gate != F_OK) {
        for (int32_t s = 0; s < t.n_slots; ++s) c[s] = 0;
        extras_count[i] = 0;
        flags[i] = gate;
        continue;
      }
      encode_one(t, f, c, eo, scratch);
      extras_count[i] = eo.n;
      flags[i] = eo.overflow ? F_EXTRAS_OVERFLOW : F_OK;
    }
  };
  if (n_threads <= 1 || n < 64) {
    work(0, n);
    return;
  }
  uint64_t nt = uint64_t(n_threads);
  if (nt > n) nt = n;
  std::vector<std::thread> threads;
  uint64_t chunk = (n + nt - 1) / nt;
  for (uint64_t k = 0; k < nt; ++k) {
    uint64_t lo = k * chunk, hi = lo + chunk > n ? n : lo + chunk;
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto &th : threads) th.join();
}

int32_t ce_n_slots(void *handle) {
  return static_cast<Table *>(handle)->n_slots;
}

}  // extern "C"
