"""Native (C++) host runtime: the SAR fast path.

The hot host-side step of the serving plane — raw SubjectAccessReview JSON →
dictionary-coded feature vector — is implemented in C++ (encoder.cpp) and
bound via ctypes. The library is compiled on first use with the system g++
(no pip deps) and cached next to the package; ``NativeEncoder`` is the
Python-facing handle.

Falls back cleanly: if no C++ toolchain is available, or the compiled policy
set needs per-request interpretation (hard literals), ``NativeEncoder.create``
returns None and callers keep the pure-Python encode path.

Blob format (little-endian; must match BlobReader in encoder.cpp):

  i32 magic "CTB4" (0x43544234)
  i32 n_slots
  3x var sections (principal, action, resource):
      i32 type_slot, i32 uid_slot, i32 n_anc, i32 anc_slots[...]
  type_map:  i32 count, { str key, i32 row }       key = "<v>\\x1f<type>"
  uid_map:   i32 count, { str key, i32 row }       key = "<v>\\x1f<type>\\x1f<id>"
  anc_map:   i32 count, { str key, i32 row, i32 nlits, i32 lits[] }
  slots:     i32 count, { u8 var, u8 deep, str attr, i32 sidx,
                          i32 present_row,
                          vocab:   i32 count, { str canon, i32 row }
                          likes:   i32 count, { i32 lit, i32 ncomps,
                                                { u8 wild, [str chunk] } }
                          cmps:    i32 count, { i32 lit, u8 op, i64 c }
                          set_has: i32 count, { str canon, i32 n, i32 lits[] }
                          dyns:    i32 count, { u8 kind (0 contains, 1 eq,
                                                2 cmp, 3 containsAny,
                                                4 containsAll), u8 op
                                                (eq: 0 == 1 !=; cmp: 0 <
                                                1 <= 2 > 3 >=; else 0),
                                                i32 lit, i32 ok, i32 err,
                                                kind<=2: tmpl
                                                kind>=3: i32 n, { tmpl } }
                          type_err: i32 count, { i32 lit, u8 want-tag } }
  tmpl = u8 kind: 0 const  { str canon }
                | 2 record { i32 n, { str name, tmpl } }   (names sorted)
                | 3 set    { i32 n, { tmpl } }             (sorted at runtime)
                | 4 slot   { u8 var, i32 n, { str comp } } (another request
                            slot's value, resolved per request; kind 1 was
                            the principal-attr special case, subsumed by 4)

  (str = i32 length + bytes)
"""

from __future__ import annotations

import ctypes
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..compiler.dyn import DynCmp, DynContainsMulti, DynEq
from ..lang.ast import WILDCARD

# flags mirrored from encoder.cpp
F_OK = 0
F_PARSE_ERROR = 1
F_SELF_ALLOW_POLICIES = 2
F_SELF_ALLOW_RBAC = 3
F_SYSTEM_SKIP = 4
F_EXTRAS_OVERFLOW = 5
F_ADM_NS_SKIP = 6  # admission: kube-system/cedar-k8s-authz-system -> allow
F_ADM_ERROR = 7  # admission: conversion error/unsupported shape -> py path

_VAR_IDX = {"principal": 0, "action": 1, "resource": 2, "context": 3}
_CMP_OPS = {"<": 0, "<=": 1, ">": 2, ">=": 3}


def _canon(vk) -> bytes:
    """Canonical byte string for a value_key; must stay in sync with the
    canon_* helpers in encoder.cpp.

    Strings (and entity type/id, and record field names) are LENGTH-
    PREFIXED: request-controlled bytes may contain the \\x1f/\\x1d
    structure separators, and without the prefix a crafted value like
    "x\\x1fsy" would alias a different composite value's canon — a
    decision-flipping false match on the native membership paths."""
    tag = vk[0]
    if tag == "b":
        return b"t" if vk[1] else b"f"
    if tag == "l":
        return b"l%d" % vk[1]
    if tag == "s":
        b = vk[1].encode("utf-8", "surrogatepass")
        return b"s%d:%s" % (len(b), b)
    if tag == "e":
        t = vk[1].encode()
        i = vk[2].encode("utf-8", "surrogatepass")
        return b"e%d:%s%d:%s" % (len(t), t, len(i), i)
    if tag == "S":
        return b"S{" + b"\x1f".join(sorted(_canon(e) for e in vk[1])) + b"}"
    if tag == "R":
        parts = []
        for k, v in vk[1]:
            kb = k.encode("utf-8", "surrogatepass")
            parts.append(b"%d:%s\x1d%s" % (len(kb), kb, _canon(v)))
        return b"R{" + b"\x1f".join(parts) + b"}"
    raise ValueError(f"cannot canonicalize value key {vk!r}")


class _BlobWriter:
    def __init__(self):
        self.parts: List[bytes] = []

    def u8(self, v: int):
        self.parts.append(struct.pack("<B", v))

    def i32(self, v: int):
        self.parts.append(struct.pack("<i", v))

    def i64(self, v: int):
        self.parts.append(struct.pack("<q", v))

    def s(self, b) -> None:
        if isinstance(b, str):
            b = b.encode("utf-8", "surrogatepass")
        self.parts.append(struct.pack("<i", len(b)))
        self.parts.append(b)

    def blob(self) -> bytes:
        return b"".join(self.parts)


def serialize_table(plan, table) -> Optional[bytes]:
    """FeatureTable + EncodePlan -> native blob, or None when value kinds
    the canon format doesn't cover fall back to Python.

    Hard literals OUTSIDE the dyn-contains class (compiler/dyn.py) do not
    disable the native plane: their lit/ok/err features simply stay
    inactive in native encodes, which can never fire the owning policy's
    rules or error clauses — and every request those rules COULD affect
    matches the policy's scope, which pack() turned into a gate rule, so
    such rows re-run the exact Python path (WORD_GATE)."""
    try:
        return _serialize_table(plan, table)
    except ValueError:
        return None


_TMPL_VAR_CODES = {"principal": 0, "action": 1, "resource": 2, "context": 3}


def _write_tmpl(w: "_BlobWriter", t) -> None:
    kind = t[0]
    if kind == "const":
        w.u8(0)
        w.s(_canon(t[1]))
    elif kind == "slot":
        w.u8(4)
        code = _TMPL_VAR_CODES.get(t[1])
        if code is None:
            raise ValueError(f"unknown template slot var {t[1]!r}")
        w.u8(code)
        w.i32(len(t[2]))
        for comp in t[2]:
            w.s(comp)
    elif kind == "record":
        w.u8(2)
        w.i32(len(t[1]))
        for name, child in t[1]:  # pre-sorted by dyn._tmpl_of
            w.s(name)
            _write_tmpl(w, child)
    elif kind == "set":
        w.u8(3)
        w.i32(len(t[1]))
        for child in t[1]:
            _write_tmpl(w, child)
    else:
        raise ValueError(f"unknown template node {t!r}")


def _serialize_table(plan, table) -> bytes:
    w = _BlobWriter()
    w.i32(0x43544234)
    w.i32(table.n_slots)

    vars3 = ("principal", "action", "resource")
    for var in vars3:
        w.i32(table.var_type_slot.get(var, -1))
        w.i32(table.var_uid_slot.get(var, -1))
        anc = table.anc_slots.get(var, ())
        w.i32(len(anc))
        for a in anc:
            w.i32(a)

    def var_key(var: str, *rest: str) -> bytes:
        return b"\x1f".join(
            [str(_VAR_IDX[var]).encode()] + [r.encode() for r in rest]
        )

    w.i32(len(table.type_vocab))
    for (var, tname), row in table.type_vocab.items():
        w.s(var_key(var, tname))
        w.i32(row)

    w.i32(len(table.uid_vocab))
    for (var, tname, eid), row in table.uid_vocab.items():
        w.s(var_key(var, tname, eid))
        w.i32(row)

    w.i32(len(table.anc_vocab))
    for (var, tname, eid), row in table.anc_vocab.items():
        w.s(var_key(var, tname, eid))
        w.i32(row)
        lits = plan.entity_in_idx.get(var, {}).get((tname, eid), ())
        w.i32(len(lits))
        for lid in lits:
            w.i32(lid)

    w.i32(len(table.scalar_slot_of))
    for slot, sidx in table.scalar_slot_of.items():
        var, path = slot
        w.u8(_VAR_IDX.get(var, 3))
        w.u8(1 if len(path) != 1 else 0)
        w.s(path[0] if len(path) == 1 else "\x1f".join(path))
        w.i32(sidx)
        w.i32(table.present_row[slot])

        vocab = table.scalar_vocab.get(slot, {})
        w.i32(len(vocab))
        for vk, row in vocab.items():
            w.s(_canon(vk))
            w.i32(row)

        likes = plan.like_idx.get(slot, ())
        w.i32(len(likes))
        for lid, pattern in likes:
            w.i32(lid)
            w.i32(len(pattern.components))
            for comp in pattern.components:
                if comp is WILDCARD:
                    w.u8(1)
                else:
                    w.u8(0)
                    w.s(comp)

        cmps = plan.cmp_idx.get(slot, ())
        w.i32(len(cmps))
        for lid, op, c in cmps:
            w.i32(lid)
            w.u8(_CMP_OPS[op])
            w.i64(c)

        sh = plan.set_has_idx.get(slot, {})
        w.i32(len(sh))
        for vk, lits in sh.items():
            w.s(_canon(vk))
            w.i32(len(lits))
            for lid in lits:
                w.i32(lid)

        dyns = [
            (spec, lid, okid, elid)
            for (lid, okid, _expr, elid), spec in zip(
                plan.hard_lits, plan.dyn_specs
            )
            if spec is not None and spec.slot == slot
        ]
        w.i32(len(dyns))
        for spec, lid, okid, elid in dyns:
            if isinstance(spec, DynEq):
                w.u8(1)
                w.u8(1 if spec.negate else 0)
            elif isinstance(spec, DynCmp):
                w.u8(2)
                w.u8(_CMP_OPS[spec.op])
            elif isinstance(spec, DynContainsMulti):
                w.u8(4 if spec.require_all else 3)
                w.u8(0)
            else:
                w.u8(0)
                w.u8(0)
            w.i32(lid)
            w.i32(okid)
            w.i32(elid)
            if isinstance(spec, DynContainsMulti):
                w.i32(len(spec.tmpls))
                for t in spec.tmpls:
                    _write_tmpl(w, t)
            else:
                _write_tmpl(w, spec.tmpl)

        type_errs = plan.type_err_idx.get(slot, ())
        w.i32(len(type_errs))
        for lid, want in type_errs:
            w.i32(lid)
            w.u8(ord(want))

    return w.blob()


_lib = None
_pylib = None  # PyDLL view for the *_pylist entries (None: not compiled in)
_lib_error: Optional[str] = None


def _load_library():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        from .build import ensure_built

        path = ensure_built()
        lib = ctypes.CDLL(str(path))
        lib.ce_load_table.restype = ctypes.c_void_p
        lib.ce_load_table.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.ce_free_table.argtypes = [ctypes.c_void_p]
        lib.ce_n_slots.restype = ctypes.c_int32
        lib.ce_n_slots.argtypes = [ctypes.c_void_p]
        lib.ce_encode_sar_batch.restype = None
        lib.ce_encode_sar_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32,
        ]
        lib.ce_encode_adm_batch.restype = None
        lib.ce_encode_adm_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        # best-effort zero-packing entries (built iff Python.h was present;
        # see build.py). A PyDLL view of the same library keeps the GIL on
        # entry — the C side harvests the list under the GIL, then releases
        # it for the threaded encode.
        global _pylib
        try:
            pylib = ctypes.PyDLL(str(path))
            pylib.ce_encode_sar_pylist.restype = None
            pylib.ce_encode_sar_pylist.argtypes = [
                ctypes.c_void_p,
                ctypes.py_object,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int32,
            ]
            pylib.ce_encode_adm_pylist.restype = None
            pylib.ce_encode_adm_pylist.argtypes = [
                ctypes.c_void_p,
                ctypes.py_object,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
            ]
            _pylib = pylib
        except (OSError, AttributeError):
            _pylib = None  # glue not compiled in: packed-buffer path only
        _lib = lib
    except Exception as e:  # no toolchain / build failure => python path
        _lib_error = str(e)
        return None
    return _lib


def native_available() -> bool:
    return _load_library() is not None


def native_error() -> Optional[str]:
    _load_library()
    return _lib_error



_encode_threads_cache: "Optional[int]" = None
_encode_threads_override: "Optional[int]" = None


def _default_encode_threads() -> int:
    """Per-batch encode thread count. An explicit set_encode_threads()
    override (the webhook CLI's --native-encode-threads flag) wins;
    otherwise CEDAR_NATIVE_THREADS pins it (operators sharing cores with
    other tenants; the pipeline bench uses 1 to isolate stage overlap —
    docs/performance.md); a malformed value is logged ONCE and ignored
    rather than crashing every native encode into the interpreter-fallback
    path. Resolved on first use and cached — this runs per micro-batch on
    the hot path; reset_encode_threads() invalidates the cache so a
    corrected env var actually takes effect."""
    global _encode_threads_cache
    if _encode_threads_override is not None:
        return _encode_threads_override
    if _encode_threads_cache is not None:
        return _encode_threads_cache
    import logging
    import os

    val = 0
    raw = os.environ.get("CEDAR_NATIVE_THREADS", "")
    if raw:
        try:
            env = int(raw)
            if env > 0:
                val = env
        except ValueError:
            logging.getLogger(__name__).warning(
                "ignoring malformed CEDAR_NATIVE_THREADS=%r (want a "
                "positive integer)",
                raw,
            )
    if val <= 0:
        val = min(max(os.cpu_count() or 1, 1), 16)
    _encode_threads_cache = val
    return val


def reset_encode_threads() -> None:
    """Invalidate the cached thread count (and any override): the next
    encode re-reads CEDAR_NATIVE_THREADS. The cache is a module global
    resolved once per process — without this hook a malformed-then-
    corrected env var (or a test that monkeypatches it) silently kept the
    stale value forever."""
    global _encode_threads_cache, _encode_threads_override
    _encode_threads_cache = None
    _encode_threads_override = None


def set_encode_threads(n: Optional[int]) -> None:
    """Pin the per-batch encode thread count, overriding the env var —
    the webhook CLI's --native-encode-threads flag. None (or <= 0) clears
    the override back to env/auto resolution."""
    global _encode_threads_override
    reset_encode_threads()
    if n is not None and n > 0:
        _encode_threads_override = int(n)

class NativeEncoder:
    """Owns one loaded native activation table; encodes raw SAR JSON batches."""

    DEFAULT_EXTRAS_CAP = 32

    def __init__(self, handle: int, n_slots: int, pad_value: int):
        self._handle = handle
        self.n_slots = n_slots
        self.pad_value = pad_value

    @classmethod
    def create(cls, packed) -> Optional["NativeEncoder"]:
        """Build a NativeEncoder for a PackedPolicySet, or None if the set
        (value kinds outside the canon format) or the environment (no g++)
        rules it out. Hard literals outside the dyn class don't: their
        policies gate to the Python path per row (see serialize_table)."""
        lib = _load_library()
        if lib is None:
            return None
        blob = serialize_table(packed.plan, packed.table)
        if blob is None:
            return None
        handle = lib.ce_load_table(blob, len(blob))
        if not handle:
            raise RuntimeError("native table load failed (blob format skew?)")
        return cls(handle, packed.table.n_slots, packed.L)

    def __del__(self):
        lib = _lib
        if lib is not None and getattr(self, "_handle", None):
            lib.ce_free_table(self._handle)
            self._handle = None

    @staticmethod
    def _check_out(name: str, arr: np.ndarray, rows: int, width: int, dtype):
        """Output-buffer contract for the *_into entries: the C side
        writes through raw pointers with a fixed row stride, so a wrong
        dtype/shape/layout is memory corruption, not an exception."""
        if arr.dtype != np.dtype(dtype):
            raise ValueError(f"{name}: want dtype {np.dtype(dtype)}, got {arr.dtype}")
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError(f"{name}: buffer must be C-contiguous")
        if arr.shape[0] < rows:
            raise ValueError(f"{name}: {arr.shape[0]} rows < batch size {rows}")
        if width is not None and (arr.ndim != 2 or arr.shape[1] != width):
            raise ValueError(f"{name}: want shape [>= {rows}, {width}], got {arr.shape}")

    def encode_batch_into(
        self,
        bodies: Sequence[bytes],
        codes: np.ndarray,
        extras: np.ndarray,
        counts: np.ndarray,
        flags: np.ndarray,
        n_threads: int = 0,
    ) -> int:
        """Encode raw SAR bodies DIRECTLY into caller-provided buffers —
        the zero-copy staging path (engine/fastpath.py hands in the
        engine's pooled, bucket-padded staging buffers so encode output
        needs no intermediate copy before the donated H2D transfer).

        codes [B >= n, n_slots] int32 and extras [B >= n, cap] int32 must
        be C-contiguous; counts [>= n] int32, flags [>= n] uint8. Only the
        first len(bodies) rows are written (extras rows are pad-filled to
        the buffer's cap); rows beyond that — bucket padding — are the
        caller's to fill. Returns the encoded row count."""
        lib = _load_library()
        assert lib is not None
        n = len(bodies)
        if n_threads <= 0:
            n_threads = _default_encode_threads()
        self._check_out("codes", codes, n, self.n_slots, np.int32)
        extras_cap = extras.shape[1] if extras.ndim == 2 else 0
        self._check_out("extras", extras, n, extras_cap, np.int32)
        self._check_out("counts", counts, n, None, np.int32)
        self._check_out("flags", flags, n, None, np.uint8)
        if n == 0:
            return 0
        c_codes = codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_extras = extras.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_counts = counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_flags = flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if _pylib is not None and type(bodies) is list:
            # zero-packing path: the C side reads the bytes objects in
            # place — no join, no per-item length loop — and pad-fills
            # every row's unused extras cells itself (extras_pad)
            _pylib.ce_encode_sar_pylist(
                self._handle,
                bodies,
                n,
                c_codes,
                c_extras,
                extras_cap,
                self.pad_value,
                c_counts,
                c_flags,
                n_threads,
            )
            return n
        # packed-buffer entry: extras arrives caller-pre-padded (the C
        # side only writes consumed cells)
        extras[:n] = self.pad_value
        buf = b"".join(bodies)
        lens = np.fromiter((len(b) for b in bodies), dtype=np.uint64, count=n)
        offsets = np.zeros((n,), dtype=np.uint64)
        np.cumsum(lens[:-1], out=offsets[1:])
        lib.ce_encode_sar_batch(
            self._handle,
            n,
            buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            c_codes,
            c_extras,
            extras_cap,
            c_counts,
            c_flags,
            n_threads,
        )
        return n

    def encode_batch(
        self,
        bodies: Sequence[bytes],
        extras_cap: int = DEFAULT_EXTRAS_CAP,
        n_threads: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Raw SAR JSON bodies -> (codes [n, S] int32, extras [n, cap] int32
        pre-padded with pad_value, extras_count [n], flags [n]).

        flags: F_OK rows are device-ready; gate rows (self-allow / system
        skip) carry the decision; F_PARSE_ERROR / F_EXTRAS_OVERFLOW rows
        need the caller's Python fallback."""
        n = len(bodies)
        if n == 0:
            return (
                np.zeros((0, self.n_slots), np.int32),
                np.full((0, extras_cap), self.pad_value, np.int32),
                np.zeros((0,), np.int32),
                np.zeros((0,), np.uint8),
            )
        # every cell of the first n rows is written by the C side (or the
        # packed-entry pre-pad in encode_batch_into): np.empty is safe
        codes = np.empty((n, self.n_slots), dtype=np.int32)
        extras = np.empty((n, extras_cap), dtype=np.int32)
        counts = np.empty((n,), dtype=np.int32)
        flags = np.empty((n,), dtype=np.uint8)
        self.encode_batch_into(bodies, codes, extras, counts, flags, n_threads)
        return codes, extras, counts, flags

    def encode_adm_batch_into(
        self,
        bodies: Sequence[bytes],
        codes: np.ndarray,
        extras: np.ndarray,
        counts: np.ndarray,
        flags: np.ndarray,
        n_threads: int = 0,
    ) -> List[str]:
        """Admission twin of encode_batch_into: encode raw AdmissionReview
        bodies into caller-provided buffers (same shape/layout contract)
        and return the per-row review uids. Only the first len(bodies)
        rows are written; bucket-padding rows are the caller's to fill."""
        lib = _load_library()
        assert lib is not None
        n = len(bodies)
        if n_threads <= 0:
            n_threads = _default_encode_threads()
        self._check_out("codes", codes, n, self.n_slots, np.int32)
        extras_cap = extras.shape[1] if extras.ndim == 2 else 0
        self._check_out("extras", extras, n, extras_cap, np.int32)
        self._check_out("counts", counts, n, None, np.int32)
        self._check_out("flags", flags, n, None, np.uint8)
        if n == 0:
            return []
        uid_buf = ctypes.create_string_buffer(n * 256)
        uid_lens = np.empty((n,), dtype=np.int32)
        c_codes = codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_extras = extras.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_counts = counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        c_flags = flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        c_uid_lens = uid_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if _pylib is not None and type(bodies) is list:
            _pylib.ce_encode_adm_pylist(
                self._handle,
                bodies,
                n,
                c_codes,
                c_extras,
                extras_cap,
                self.pad_value,
                c_counts,
                c_flags,
                uid_buf,
                c_uid_lens,
                n_threads,
            )
        else:
            extras[:n] = self.pad_value  # packed entry: caller pre-pads
            buf = b"".join(bodies)
            lens = np.fromiter(
                (len(b) for b in bodies), dtype=np.uint64, count=n
            )
            offsets = np.zeros((n,), dtype=np.uint64)
            np.cumsum(lens[:-1], out=offsets[1:])
            lib.ce_encode_adm_batch(
                self._handle,
                n,
                buf,
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                c_codes,
                c_extras,
                extras_cap,
                c_counts,
                c_flags,
                uid_buf,
                c_uid_lens,
                n_threads,
            )
        raw = uid_buf.raw
        return [
            raw[i * 256 : i * 256 + uid_lens[i]].decode("utf-8", "replace")
            for i in range(n)
        ]

    def encode_adm_batch(
        self,
        bodies: Sequence[bytes],
        extras_cap: int = DEFAULT_EXTRAS_CAP,
        n_threads: int = 0,
    ):
        """Raw AdmissionReview JSON bodies -> (codes, extras, extras_count,
        flags, uids). Same contract as encode_batch plus: uids[i] is the
        review uid (str) for F_OK / F_ADM_NS_SKIP rows; F_PARSE_ERROR /
        F_ADM_ERROR / F_EXTRAS_OVERFLOW rows need the Python fallback."""
        n = len(bodies)
        if n == 0:
            return (
                np.zeros((0, self.n_slots), np.int32),
                np.full((0, extras_cap), self.pad_value, np.int32),
                np.zeros((0,), np.int32),
                np.zeros((0,), np.uint8),
                [],
            )
        codes = np.empty((n, self.n_slots), dtype=np.int32)
        extras = np.empty((n, extras_cap), dtype=np.int32)
        counts = np.empty((n,), dtype=np.int32)
        flags = np.empty((n,), dtype=np.uint8)
        uids = self.encode_adm_batch_into(
            bodies, codes, extras, counts, flags, n_threads
        )
        return codes, extras, counts, flags, uids
