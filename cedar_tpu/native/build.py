"""On-demand build of the native encoder library.

Compiles encoder.cpp with the system C++ toolchain into a shared library
cached under ``cedar_tpu/native/_build/`` keyed by a source hash, so edits
to the .cpp transparently rebuild and repeated imports are free. No pip
dependencies: plain g++ (or $CXX) + ctypes."""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import threading

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "encoder.cpp"
_BUILD_DIR = _HERE / "_build"
_LOCK = threading.Lock()


def _glue_include() -> str:
    """Python include dir when this interpreter's headers are present
    (enables the *_pylist zero-packing entries), else ''."""
    import sysconfig

    inc = sysconfig.get_paths().get("include")
    if inc and os.path.exists(os.path.join(inc, "Python.h")):
        return inc
    return ""


def _source_hash() -> str:
    import sysconfig

    arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
    h = hashlib.sha256(_SRC.read_bytes())
    h.update(arch.encode())
    if _glue_include():
        # the glue compiles PyList/PyObject struct-offset macros for THIS
        # interpreter's ABI: key the cache on it so a different
        # interpreter (or a headers-appeared-later host) rebuilds
        h.update(b"pyglue:")
        h.update(str(sysconfig.get_config_var("SOABI")).encode())
    return h.hexdigest()[:16]


def library_path() -> pathlib.Path:
    return _BUILD_DIR / f"libcedar_native_{_source_hash()}.so"


def ensure_built() -> pathlib.Path:
    """Compile (once) and return the shared-library path."""
    out = library_path()
    if out.exists():
        return out
    with _LOCK:
        if out.exists():
            return out
        _BUILD_DIR.mkdir(exist_ok=True)
        cxx = os.environ.get("CXX", "g++")
        # CEDAR_NATIVE_ARCH=x86-64 (etc.) builds a portable binary — set it
        # for container images so the .so survives a host-CPU change; the
        # default tunes for the build machine
        arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
        tmp = out.with_suffix(".so.tmp")
        cmd = [
            cxx,
            "-O3",
            f"-march={arch}",
            "-fno-plt",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-pthread",
            str(_SRC),
            "-o",
            str(tmp),
        ]
        # CPython glue (the *_pylist zero-packing entries) is best-effort:
        # compiled in when this interpreter's headers are present, dropped
        # otherwise — the ctypes loader probes for the symbols and falls
        # back to the packed-buffer entries (native/__init__.py)
        inc = _glue_include()
        glue = ["-DCEDAR_PY_GLUE", f"-I{inc}"] if inc else []
        try:
            subprocess.run(
                cmd[:1] + glue + cmd[1:], check=True, capture_output=True,
                text=True,
            )
        except subprocess.CalledProcessError:
            if not glue:
                raise
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
        # drop stale builds of older source revisions
        for old in _BUILD_DIR.glob("libcedar_native_*.so"):
            if old != out:
                try:
                    old.unlink()
                except OSError:
                    pass
    return out
