"""On-demand build of the native encoder library.

Compiles encoder.cpp with the system C++ toolchain into a shared library
cached under ``cedar_tpu/native/_build/`` keyed by a source hash, so edits
to the .cpp transparently rebuild and repeated imports are free. No pip
dependencies: plain g++ (or $CXX) + ctypes."""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import threading

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "encoder.cpp"
_BUILD_DIR = _HERE / "_build"
_LOCK = threading.Lock()


def _source_hash() -> str:
    arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
    h = hashlib.sha256(_SRC.read_bytes())
    h.update(arch.encode())
    return h.hexdigest()[:16]


def library_path() -> pathlib.Path:
    return _BUILD_DIR / f"libcedar_native_{_source_hash()}.so"


def ensure_built() -> pathlib.Path:
    """Compile (once) and return the shared-library path."""
    out = library_path()
    if out.exists():
        return out
    with _LOCK:
        if out.exists():
            return out
        _BUILD_DIR.mkdir(exist_ok=True)
        cxx = os.environ.get("CXX", "g++")
        # CEDAR_NATIVE_ARCH=x86-64 (etc.) builds a portable binary — set it
        # for container images so the .so survives a host-CPU change; the
        # default tunes for the build machine
        arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
        tmp = out.with_suffix(".so.tmp")
        cmd = [
            cxx,
            "-O3",
            f"-march={arch}",
            "-fno-plt",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-pthread",
            str(_SRC),
            "-o",
            str(tmp),
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
        # drop stale builds of older source revisions
        for old in _BUILD_DIR.glob("libcedar_native_*.so"):
            if old != out:
                try:
                    old.unlink()
                except OSError:
                    pass
    return out
