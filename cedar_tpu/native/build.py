"""On-demand build of the native encoder library.

Compiles encoder.cpp with the system C++ toolchain into a shared library
cached under ``cedar_tpu/native/_build/`` keyed by a source hash, so edits
to the .cpp transparently rebuild and repeated imports are free. No pip
dependencies: plain g++ (or $CXX) + ctypes."""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import threading

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "encoder.cpp"
_BUILD_DIR = _HERE / "_build"
_LOCK = threading.Lock()


def _glue_include() -> str:
    """Python include dir when this interpreter's headers are present
    (enables the *_pylist zero-packing entries), else ''."""
    import sysconfig

    inc = sysconfig.get_paths().get("include")
    if inc and os.path.exists(os.path.join(inc, "Python.h")):
        return inc
    return ""


def _source_hash(with_glue: bool) -> str:
    import sysconfig

    arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
    h = hashlib.sha256(_SRC.read_bytes())
    h.update(arch.encode())
    if with_glue:
        # the glue compiles PyList/PyObject struct-offset macros for THIS
        # interpreter's ABI: key the cache on it so a different
        # interpreter (or a headers-appeared-later host) rebuilds
        h.update(b"pyglue:")
        h.update(str(sysconfig.get_config_var("SOABI")).encode())
    return h.hexdigest()[:16]


def library_path(with_glue: bool = None) -> pathlib.Path:
    """The cache path for a (source, arch, glue?) build. The glue state is
    part of the FILENAME, so a glueless fallback build can never occupy
    the glue-tagged slot: a transient toolchain failure leaves the glue
    path absent and the next import retries the full glue compile instead
    of being pinned to the slower packed-buffer entries forever."""
    if with_glue is None:
        with_glue = bool(_glue_include())
    tag = "glue_" if with_glue else ""
    return _BUILD_DIR / f"libcedar_native_{tag}{_source_hash(with_glue)}.so"


def _compile(out: pathlib.Path, glue_inc: str) -> None:
    cxx = os.environ.get("CXX", "g++")
    # CEDAR_NATIVE_ARCH=x86-64 (etc.) builds a portable binary — set it
    # for container images so the .so survives a host-CPU change; the
    # default tunes for the build machine
    arch = os.environ.get("CEDAR_NATIVE_ARCH", "native")
    tmp = out.with_suffix(".so.tmp")
    cmd = [
        cxx,
        "-O3",
        f"-march={arch}",
        "-fno-plt",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
    ]
    if glue_inc:
        cmd += ["-DCEDAR_PY_GLUE", f"-I{glue_inc}"]
    cmd += [str(_SRC), "-o", str(tmp)]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, out)


def ensure_built() -> pathlib.Path:
    """Compile (once) and return the shared-library path.

    CPython glue (the *_pylist zero-packing entries) is best-effort:
    compiled in when this interpreter's headers are present, dropped on
    compile failure — the ctypes loader probes for the symbols and falls
    back to the packed-buffer entries (native/__init__.py). The fallback
    build is cached under the GLUELESS filename, so the glue compile is
    retried on the next import rather than permanently pinned off."""
    out = library_path()
    if out.exists():
        return out
    with _LOCK:
        if out.exists():
            return out
        _BUILD_DIR.mkdir(exist_ok=True)
        inc = _glue_include()
        try:
            _compile(out, inc)
        except subprocess.CalledProcessError:
            if not inc:
                raise
            # glue compile failed (e.g. transient toolchain breakage):
            # build without it at the glueless cache slot
            out = library_path(with_glue=False)
            if not out.exists():
                _compile(out, "")
        # drop stale builds of older source revisions — but keep the
        # glueless fallback alongside a glue request, and vice versa: the
        # two names can legitimately coexist across retry cycles
        keep = {library_path(with_glue=False), library_path(with_glue=True)}
        for old in _BUILD_DIR.glob("libcedar_native_*.so"):
            if old not in keep:
                try:
                    old.unlink()
                except OSError:
                    pass
    return out
