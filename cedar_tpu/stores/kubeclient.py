"""Minimal kubeconfig-driven apiserver client — stdlib only (urllib + ssl).

One TLS/auth surface shared by every live-cluster consumer: the CRD policy
store's list+watch transport (stores/crd.py), the converter CLI's RBAC
listing (reference /root/reference/cmd/converter/main.go:45-58), and the
schema-generator CLI's /openapi/v3 fetch (reference
cmd/schema-generator/main.go:64-78, internal/schema/convert/openapi.go:36-88).

Supports the kubeconfig auth shapes the reference's clientcmd path covers in
this deployment: CA data/file (or insecure-skip-tls-verify), bearer token,
and client certificate data/files.
"""

from __future__ import annotations

import base64
import json
import ssl
import tempfile
import urllib.error
import urllib.request
from typing import Optional

from ..server.backoff import Backoff, retry_call


class KubeConfigClient:
    """HTTPS client for one apiserver, built from a kubeconfig file."""

    def __init__(self, kubeconfig_path: str, context: str = ""):
        import yaml

        with open(kubeconfig_path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context", "")
        ctx = next(
            c["context"] for c in cfg.get("contexts", []) if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"]
            for c in cfg.get("clusters", [])
            if c["name"] == ctx["cluster"]
        )
        user = next(
            u["user"] for u in cfg.get("users", []) if u["name"] == ctx["user"]
        )
        self.server = cluster["server"].rstrip("/")
        self._ssl = ssl.create_default_context()
        if cluster.get("certificate-authority-data"):
            self._ssl.load_verify_locations(
                cadata=base64.b64decode(
                    cluster["certificate-authority-data"]
                ).decode()
            )
        elif cluster.get("certificate-authority"):
            self._ssl.load_verify_locations(cafile=cluster["certificate-authority"])
        if cluster.get("insecure-skip-tls-verify"):
            self._ssl.check_hostname = False
            self._ssl.verify_mode = ssl.CERT_NONE
        if self.server.startswith("http://"):
            # plain-HTTP apiserver (tests / kubectl-proxy): no TLS context,
            # and any configured client certs are unusable — ignore them
            self._ssl = None
        self._token = user.get("token", "")
        self._cert_files = []
        cert = user.get("client-certificate-data")
        key = user.get("client-key-data")
        if self._ssl is None:
            pass
        elif cert and key:
            cf = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            cf.write(base64.b64decode(cert))
            cf.close()
            kf = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            kf.write(base64.b64decode(key))
            kf.close()
            self._ssl.load_cert_chain(cf.name, kf.name)
            self._cert_files = [cf.name, kf.name]
        elif user.get("client-certificate") and user.get("client-key"):
            self._ssl.load_cert_chain(
                user["client-certificate"], user["client-key"]
            )

    def open(self, url: str, timeout: Optional[float]):
        """Open an absolute URL (already including self.server)."""
        req = urllib.request.Request(url)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        return urllib.request.urlopen(req, context=self._ssl, timeout=timeout)

    def get_json(self, path: str, timeout: float = 30.0, attempts: int = 3):
        """GET an apiserver-relative path (e.g. ``/openapi/v3``) -> parsed
        JSON, retrying transient failures (connection errors, timeouts,
        5xx) with decorrelated-jitter backoff. GETs are idempotent, so the
        retry is always safe; 4xx responses are the caller's problem and
        re-raise immediately."""

        def _get():
            try:
                with self.open(f"{self.server}{path}", timeout=timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    raise _NoRetry(e) from e
                raise

        try:
            return retry_call(
                _get,
                attempts=max(1, attempts),
                retry_on=(urllib.error.URLError, OSError, TimeoutError),
                backoff=Backoff(base_s=0.25, cap_s=5.0),
            )
        except _NoRetry as e:
            raise e.error from None


class _NoRetry(Exception):
    """Wraps a terminal (non-retryable) HTTP error through retry_call."""

    def __init__(self, error):
        super().__init__(str(error))
        self.error = error
