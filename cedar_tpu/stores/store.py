"""Policy store interface and tiered evaluation semantics.

Behavior parity with reference internal/server/store/store.go:
  * PolicyStore = {initial_policy_load_complete, policy_set, name}
  * TieredPolicyStores.is_authorized walks stores first-to-last and stops at
    the first store yielding an explicit signal (any reasons OR any errors);
    the last store's decision applies otherwise (store.go:25-42).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Protocol, Tuple, runtime_checkable

from ..lang.authorize import DENY, Diagnostics, PolicySet
from ..lang.entities import EntityMap
from ..lang.eval import Request

log = logging.getLogger(__name__)


@runtime_checkable
class PolicyStore(Protocol):
    def initial_policy_load_complete(self) -> bool:
        """While False the authorizer emits NoOpinion (admission allows)."""
        ...

    def policy_set(self) -> PolicySet:
        ...

    def name(self) -> str:
        ...


class TieredPolicyStores:
    def __init__(
        self,
        stores: List[PolicyStore],
        validation_mode: Optional[str] = None,
    ):
        self.stores = list(stores)
        # load-time analysis posture (CedarConfig.validationMode); None
        # disables the gate entirely (tests, bare construction)
        self.validation_mode = validation_mode
        # the last AnalysisReport the gate produced (served by the
        # /debug/analysis endpoint); None until the first analyzed load
        self.last_analysis = None
        # cache_generation() proxy state for stores without a
        # content_generation counter: store index -> [last PolicySet,
        # monotonic counter]. The strong reference is the point — it keeps
        # the last-seen set alive so an identity change can never be
        # confused with id() reuse after garbage collection.
        self._gen_lock = threading.Lock()
        self._gen_proxies: dict = {}

    def analyzed_policy_sets(self) -> List[PolicySet]:
        """Tiers for ENGINE COMPILATION after the load-time analysis gate
        (analysis/loadgate.py): strict raises AnalysisRejected (callers
        keep serving their previous compiled set), partial returns tiers
        with the offending policies dropped, permissive returns the tiers
        unchanged but publishes findings/metrics. With no validation mode
        set, this is exactly the raw policy_set() list.

        The gate shapes what the compiler sees; the interpreter walk
        below (is_authorized) always evaluates the stores' raw sets. On
        the TPU backend decisions come from the compiled set, so partial
        REMOVES dropped policies from served decisions — a dropped
        forbid weakens enforcement (docs/analysis.md)."""
        tiers = [s.policy_set() for s in self.stores]
        if not self.validation_mode:
            return tiers
        from ..analysis.loadgate import enforce

        try:
            tiers, report = enforce(tiers, self.validation_mode)
        except Exception as e:
            # strict rejection carries its report for the debug endpoint
            report = getattr(e, "report", None)
            if report is not None:
                self.last_analysis = report
            raise
        self.last_analysis = report
        return tiers

    def cache_generation(self) -> tuple:
        """Composite policy-set generation for decision-cache invalidation
        (cedar_tpu/cache): the tuple of every tier's content generation.
        ANY store reload changes the tuple, so cached decisions computed
        under the old corpus die lazily at their next lookup — no scan.

        Stores without a content_generation counter contribute a proxy
        counter that bumps whenever their policy_set() IDENTITY changes:
        reloaders swap the set object on content change, so identity moves
        with content. The last-seen set is held by strong reference, so the
        ``is`` comparison can never be fooled by id() reuse after garbage
        collection — a swap always invalidates. A store that builds a
        fresh PolicySet per call bumps every lookup, which safely disables
        caching for that tier rather than serving stale entries."""
        parts = []
        for i, store in enumerate(self.stores):
            gen = getattr(store, "content_generation", None)
            if gen is not None:
                parts.append(gen())
                continue
            ps = store.policy_set()
            with self._gen_lock:
                proxy = self._gen_proxies.get(i)
                if proxy is None or proxy[0] is not ps:
                    proxy = [ps, (proxy[1] + 1) if proxy else 0]
                    self._gen_proxies[i] = proxy
                parts.append(proxy[1])
        return tuple(parts)

    def __iter__(self):
        return iter(self.stores)

    def __len__(self):
        return len(self.stores)

    def is_authorized(
        self, entities: EntityMap, req: Request
    ) -> Tuple[str, Diagnostics]:
        decision, diagnostic = DENY, Diagnostics()
        for i, store in enumerate(self.stores):
            try:
                decision, diagnostic = store.policy_set().is_authorized(
                    entities, req
                )
            except Exception as e:  # noqa: BLE001 — one sick tier must not 500
                # a raising store reads as Deny-with-error for its tier: the
                # error is an explicit signal (the walk stops here, matching
                # the evaluator's per-policy error semantics), and the
                # authorizer maps errors-without-reasons to NoOpinion — so a
                # crashing tier degrades to "no opinion, error recorded"
                # instead of crashing the handler
                log.exception("policy store %s evaluation failed", store.name())
                decision, diagnostic = DENY, Diagnostics(
                    errors=[f"store {store.name()}: {e}"]
                )
            if i == len(self.stores) - 1:
                break
            if decision == DENY and not diagnostic.reasons and not diagnostic.errors:
                continue  # no explicit signal in this tier; fall through
            break
        return decision, diagnostic


class MemoryStore:
    """Immutable in-memory store, always or never ready — the test fake and
    the building block for static policy holders (reference memory.go:17)."""

    def __init__(self, name: str, policy_set: PolicySet, load_complete: bool = True):
        self._name = name
        self._policies = policy_set
        self._load_complete = load_complete

    @classmethod
    def from_source(
        cls, filename: str, document: str, load_complete: bool = True
    ) -> "MemoryStore":
        return cls(filename, PolicySet.from_source(document, filename), load_complete)

    def policy_set(self) -> PolicySet:
        return self._policies

    def initial_policy_load_complete(self) -> bool:
        return self._load_complete

    def name(self) -> str:
        return self._name

    def content_generation(self) -> int:
        """Monotonic counter bumped on content change; immutable stores are
        always generation 0. Reloaders key recompilation on this instead of
        re-hashing the policy corpus every tick."""
        return 0


class StaticStore(MemoryStore):
    """A bare PolicySet holder, always ready (reference memory.go:42-54)."""

    def __init__(self, policy_set: PolicySet):
        super().__init__("StaticStore", policy_set, True)
