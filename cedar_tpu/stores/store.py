"""Policy store interface and tiered evaluation semantics.

Behavior parity with reference internal/server/store/store.go:
  * PolicyStore = {initial_policy_load_complete, policy_set, name}
  * TieredPolicyStores.is_authorized walks stores first-to-last and stops at
    the first store yielding an explicit signal (any reasons OR any errors);
    the last store's decision applies otherwise (store.go:25-42).
"""

from __future__ import annotations

import logging
from typing import List, Protocol, Tuple, runtime_checkable

from ..lang.authorize import DENY, Diagnostics, PolicySet
from ..lang.entities import EntityMap
from ..lang.eval import Request

log = logging.getLogger(__name__)


@runtime_checkable
class PolicyStore(Protocol):
    def initial_policy_load_complete(self) -> bool:
        """While False the authorizer emits NoOpinion (admission allows)."""
        ...

    def policy_set(self) -> PolicySet:
        ...

    def name(self) -> str:
        ...


class TieredPolicyStores:
    def __init__(self, stores: List[PolicyStore]):
        self.stores = list(stores)

    def __iter__(self):
        return iter(self.stores)

    def __len__(self):
        return len(self.stores)

    def is_authorized(
        self, entities: EntityMap, req: Request
    ) -> Tuple[str, Diagnostics]:
        decision, diagnostic = DENY, Diagnostics()
        for i, store in enumerate(self.stores):
            try:
                decision, diagnostic = store.policy_set().is_authorized(
                    entities, req
                )
            except Exception as e:  # noqa: BLE001 — one sick tier must not 500
                # a raising store reads as Deny-with-error for its tier: the
                # error is an explicit signal (the walk stops here, matching
                # the evaluator's per-policy error semantics), and the
                # authorizer maps errors-without-reasons to NoOpinion — so a
                # crashing tier degrades to "no opinion, error recorded"
                # instead of crashing the handler
                log.exception("policy store %s evaluation failed", store.name())
                decision, diagnostic = DENY, Diagnostics(
                    errors=[f"store {store.name()}: {e}"]
                )
            if i == len(self.stores) - 1:
                break
            if decision == DENY and not diagnostic.reasons and not diagnostic.errors:
                continue  # no explicit signal in this tier; fall through
            break
        return decision, diagnostic


class MemoryStore:
    """Immutable in-memory store, always or never ready — the test fake and
    the building block for static policy holders (reference memory.go:17)."""

    def __init__(self, name: str, policy_set: PolicySet, load_complete: bool = True):
        self._name = name
        self._policies = policy_set
        self._load_complete = load_complete

    @classmethod
    def from_source(
        cls, filename: str, document: str, load_complete: bool = True
    ) -> "MemoryStore":
        return cls(filename, PolicySet.from_source(document, filename), load_complete)

    def policy_set(self) -> PolicySet:
        return self._policies

    def initial_policy_load_complete(self) -> bool:
        return self._load_complete

    def name(self) -> str:
        return self._name

    def content_generation(self) -> int:
        """Monotonic counter bumped on content change; immutable stores are
        always generation 0. Reloaders key recompilation on this instead of
        re-hashing the policy corpus every tick."""
        return 0


class StaticStore(MemoryStore):
    """A bare PolicySet holder, always ready (reference memory.go:42-54)."""

    def __init__(self, policy_set: PolicySet):
        super().__init__("StaticStore", policy_set, True)
