"""Amazon Verified Permissions policy store.

Behavior parity with /root/reference
internal/server/store/verified_permissions.go: ListPolicies paginator +
GetPolicy per policy, full set rebuilt on a ticker, ready after first load.

The AWS client is injected (any object with list_policy_ids(store_id) and
get_policy_statement(store_id, policy_id)); boto3 is not available in this
image, so the default constructor raises unless a client is supplied — tests
and air-gapped deployments inject their own.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Protocol

from ..lang.authorize import PolicySet
from ..lang.lexer import ParseError
from ..lang.parser import parse_policies

log = logging.getLogger(__name__)


class AVPClient(Protocol):
    def list_policy_ids(self, policy_store_id: str) -> List[str]:
        ...

    def get_policy_statement(self, policy_store_id: str, policy_id: str) -> str:
        ...


class Boto3AVPClient:
    """Adapter over boto3 verifiedpermissions (optional dependency)."""

    def __init__(self, region: str = "", profile: str = ""):
        try:
            import boto3  # type: ignore
        except ImportError as e:  # pragma: no cover - boto3 not in image
            raise ImportError(
                "boto3 is required for the verifiedPermissions store; install "
                "it or inject a custom AVPClient"
            ) from e
        session = boto3.Session(
            **({"region_name": region} if region else {}),
            **({"profile_name": profile} if profile else {}),
        )
        self._client = session.client("verifiedpermissions")

    def list_policy_ids(self, policy_store_id: str) -> List[str]:
        ids: List[str] = []
        paginator = self._client.get_paginator("list_policies")
        for page in paginator.paginate(policyStoreId=policy_store_id):
            for p in page.get("policies", []):
                ids.append(p["policyId"])
        return ids

    def get_policy_statement(self, policy_store_id: str, policy_id: str) -> str:
        resp = self._client.get_policy(
            policyStoreId=policy_store_id, policyId=policy_id
        )
        definition = resp.get("definition", {})
        static = definition.get("static")
        if static:
            return static.get("statement", "")
        return ""


class VerifiedPermissionsPolicyStore:
    def __init__(
        self,
        policy_store_id: str,
        client: Optional[AVPClient] = None,
        refresh_interval_s: float = 300.0,
        region: str = "",
        profile: str = "",
        start_ticker: bool = True,
    ):
        self.policy_store_id = policy_store_id
        self._client = client or Boto3AVPClient(region, profile)
        self.refresh_interval_s = refresh_interval_s
        self._policies = PolicySet()
        self._generation = 0
        self._lock = threading.Lock()
        self._load_complete = False
        self._stop = threading.Event()
        self.load_policies()
        if start_ticker:
            threading.Thread(
                target=self._reload_loop, name="avp-store-reload", daemon=True
            ).start()

    def close(self) -> None:
        self._stop.set()

    def _reload_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            self.load_policies()

    def load_policies(self) -> None:
        import hashlib

        digest = hashlib.sha256()
        statements = []
        try:
            # sorted: ListPolicies pagination order is not canonical, and
            # the digest must not depend on it
            ids = sorted(self._client.list_policy_ids(self.policy_store_id))
            for pid in ids:
                statement = self._client.get_policy_statement(
                    self.policy_store_id, pid
                )
                if not statement:
                    continue
                # length prefixes keep (pid, statement) boundaries
                # unambiguous in the digest
                digest.update(f"{len(pid)}:".encode())
                digest.update(pid.encode())
                digest.update(f"{len(statement)}:".encode())
                digest.update(statement.encode())
                statements.append((pid, statement))
        except Exception as e:
            log.error("AVP store load failed: %s", e)
            return
        fp = digest.hexdigest()
        if fp == getattr(self, "_content_digest", None):
            # unchanged corpus: skip the re-parse entirely
            self._load_complete = True
            return
        ps = PolicySet()
        for pid, statement in statements:
            try:
                for i, p in enumerate(parse_policies(statement, pid)):
                    ps.add(p, policy_id=f"{pid}.policy{i}")
            except ParseError as e:
                log.error("AVP policy %s parse error: %s", pid, e)
        with self._lock:
            self._policies = ps
            self._content_digest = fp
            self._generation += 1
        self._load_complete = True

    def policy_set(self) -> PolicySet:
        with self._lock:
            return self._policies

    def initial_policy_load_complete(self) -> bool:
        return self._load_complete

    def name(self) -> str:
        return "VerifiedPermissionsStore"

    def content_generation(self) -> int:
        with self._lock:
            return self._generation
