"""CRD policy store: watches cedar.k8s.aws/v1alpha1 Policy objects.

Behavior parity with /root/reference internal/server/store/crd.go:
  * not ready until the initial list completes (crd.go:183-186); the store
    first poll-waits for its kubeconfig file to exist (bootstrap circular
    dependency with the apiserver, crd.go:130-144)
  * add/update/delete events re-parse policy text into the shared set under
    a lock; policy ids are "<name><idx>-<uid>" (crd.go:60)
  * a parse error logs and skips that Policy object

The watch transport is pluggable: KubeAPIWatchSource speaks list+watch to a
real apiserver using a kubeconfig (stdlib TLS, no client library); tests
drive a fake source directly.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.error
from typing import Callable, List, Optional, Protocol

from ..apis.v1alpha1 import GROUP, PolicyObject, VERSION
from ..chaos.registry import chaos_fire
from ..lang.authorize import PolicySet
from ..lang.lexer import ParseError
from ..lang.parser import parse_policies
from ..server.backoff import Backoff
from .quarantine import quarantine_registry

log = logging.getLogger(__name__)

Event = tuple  # (type: "ADDED"|"MODIFIED"|"DELETED"|"ERROR", PolicyObject)


class WatchExpired(Exception):
    """The watch's resourceVersion is no longer valid; a fresh list is
    required (kube 410 Gone / ERROR watch event)."""


class PolicyWatchSource(Protocol):
    def list(self) -> List[PolicyObject]:
        ...

    def watch(self, on_event: Callable[[str, PolicyObject], None], stop) -> None:
        """Blocks, delivering events until `stop` (threading.Event) is set."""
        ...


class CRDPolicyStore:
    def __init__(
        self,
        source: Optional[PolicyWatchSource] = None,
        kubeconfig_path: Optional[str] = None,
        kubeconfig_context: str = "",
        start: bool = True,
        validation_mode: Optional[str] = None,
    ):
        self._source = source
        self._kubeconfig_path = kubeconfig_path or os.environ.get("KUBECONFIG", "")
        self._kubeconfig_context = kubeconfig_context
        # load-time lowerability gate per Policy object
        # (CedarConfig.validationMode; analysis/loadgate.py): strict
        # rejects the whole object like a parse error, partial drops only
        # the offending policies, permissive logs + counts. None skips the
        # analysis entirely. Whole-set passes (shadowing/conflicts) need
        # the full tier view and run at engine load instead.
        self._validation_mode = validation_mode
        self._policies = PolicySet()
        self._ids_by_object: dict = {}  # object name -> [policy ids]
        # object name -> (uid, content, is_candidate): generation bumps
        # ONLY when this map changes, so watch reconnect relists and
        # metadata-only MODIFIED events never trigger a TPU recompile.
        # is_candidate is part of the key because flipping the rollout
        # label IS a serving-set change (the object enters/leaves the live
        # corpus) even though uid+content are untouched.
        self._content_by_object: dict = {}
        # Policy objects labeled cedar.k8s.aws/rollout=candidate: EXCLUDED
        # from the live serving set and held here for the shadow-rollout
        # controller to stage (rollout/source.candidate_tiers_from_objects)
        self._candidate_objects: dict = {}
        # object names THIS store quarantined: a relist after a watch
        # outage must clear entries for objects deleted while disconnected
        # (their DELETED events never arrived)
        self._quarantined: set = set()
        self._generation = 0
        self._lock = threading.Lock()
        self._load_complete = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._start_thread()

    def _start_thread(self) -> None:
        self._thread = threading.Thread(
            target=self._watch_main, name="crd-store", daemon=True
        )
        self._thread.start()

    def watch_threads(self) -> list:
        """The list+watch worker thread(s) (supervisor liveness probe)."""
        return [self._thread] if self._thread is not None else []

    def revive(self, force: bool = False) -> bool:
        """Restart a dead (or, forced, wedged) list+watch thread
        (supervisor hook). The fresh thread relists from scratch — the
        content-keyed generation means an unchanged corpus relist never
        recompiles downstream. A superseded old thread exits at its next
        loop check."""
        t = self._thread
        if self._stop.is_set():
            return False
        if t is not None and t.is_alive() and not force:
            return False
        log.warning("CRD store: restarting list+watch thread")
        self._start_thread()
        return True

    def _watch_main(self) -> None:
        try:
            self._populate_policies()
        except BaseException:  # noqa: BLE001 — visibility, then unwind
            try:
                from ..server.metrics import record_worker_death

                record_worker_death("crd.watch")
            except Exception:  # noqa: BLE001 — must not mask the death
                pass
            log.critical("CRD watch thread died on an uncaught exception")
            raise

    def _superseded(self) -> bool:
        """True when this thread's generation was replaced by revive()
        (direct test calls from the owning thread are never superseded)."""
        t = self._thread
        return t is not None and t is not threading.current_thread()

    def close(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------- lifecycle

    def _populate_policies(self) -> None:
        if self._source is None:
            # bootstrap: wait for the kubeconfig file to exist (5s poll)
            while not self._stop.is_set():
                if self._kubeconfig_path and os.path.exists(self._kubeconfig_path):
                    break
                log.info(
                    "CRD store waiting for kubeconfig %s", self._kubeconfig_path
                )
                if self._stop.wait(5.0):
                    return
            try:
                self._source = KubeAPIWatchSource(
                    self._kubeconfig_path, self._kubeconfig_context
                )
            except Exception as e:  # pragma: no cover - env specific
                log.error("CRD store: failed to build kube client: %s", e)
                return
        # decorrelated-jitter backoff shared by the initial list and the
        # watch reconnect loop: an apiserver blip must neither kill the
        # store permanently (the old initial-list behavior) nor invite a
        # synchronized fixed-cadence retry herd
        backoff = Backoff(base_s=1.0, cap_s=30.0)
        while not self._stop.is_set() and not self._superseded():
            try:
                self._relist()
                break
            except Exception as e:
                log.error("CRD store: initial list failed, retrying: %s", e)
                if self._stop.wait(backoff.next()):
                    return
        else:
            return
        self._load_complete = True
        backoff.reset()
        while not self._stop.is_set() and not self._superseded():
            try:
                self._source.watch(self._dispatch, self._stop)
                backoff.reset()  # a clean watch cycle proves the link healthy
            except WatchExpired as e:
                # stale resourceVersion (apiserver compaction / 410 Gone):
                # drop the bookmark and rebuild from a fresh list
                log.warning("CRD store: watch expired (%s), relisting", e)
                self._try_relist(backoff)
            except Exception as e:
                log.error("CRD store: watch error, retrying: %s", e)
                if self._stop.wait(backoff.next()):
                    return
                self._try_relist(backoff)

    def _try_relist(self, backoff: Optional[Backoff] = None) -> None:
        try:
            reset = getattr(self._source, "reset_resource_version", None)
            if reset is not None:
                reset()
            self._relist()
            if backoff is not None:
                backoff.reset()
        except Exception as e:
            log.error("CRD store: relist failed: %s", e)
            self._stop.wait(backoff.next() if backoff is not None else 2.0)

    def _relist(self) -> None:
        chaos_fire("store.crd.relist")
        objs = self._source.list()
        # objects deleted while the watch was down never sent a DELETED
        # event: their quarantine entries leave with them at the relist
        listed = {obj.name for obj in objs}
        for name in self._quarantined - listed:
            quarantine_registry().clear("crd", name)
            self._quarantined.discard(name)
        with self._lock:
            ps = PolicySet()
            ids_by_object: dict = {}
            content_by_object: dict = {}
            candidate_objects: dict = {}
            for obj in objs:
                if self._is_candidate(obj):
                    candidate_objects[obj.name] = obj
                    content_by_object[obj.name] = (
                        obj.uid, obj.spec.content, True,
                    )
                    continue
                uid, content = obj.uid, obj.spec.content
                policies = self._parse(obj)
                if policies is None:
                    # poison-object quarantine with last-known-good
                    # retention: the object is broken (parse failure or
                    # strict-gate rejection), but its PREVIOUS content
                    # served fine — keep serving that instead of silently
                    # dropping the object's policies from the corpus. The
                    # retained (uid, content) keeps the live-view
                    # generation stable, so no recompile churns either.
                    prev = self._content_by_object.get(obj.name)
                    if prev is None or prev[2]:
                        continue  # nothing good to retain
                    uid, content = prev[0], prev[1]
                    try:
                        policies = parse_policies(content, obj.name)
                    except ParseError:
                        continue  # previous content gone bad too: drop
                ids = []
                for i, p in enumerate(policies):
                    pid = f"{obj.name}{i}-{uid}"
                    ps.add(p, policy_id=pid)
                    ids.append(pid)
                ids_by_object[obj.name] = ids
                content_by_object[obj.name] = (uid, content, False)
            self._policies = ps
            self._ids_by_object = ids_by_object
            self._candidate_objects = candidate_objects
            # generation compares the LIVE view only: a candidate-labeled
            # object's content is not served, so a candidate edit arriving
            # via a reconnect relist must not recompile the engines (or —
            # after a promotion — revert the promoted compiled set through
            # the reloader). Label flips still bump: the object enters or
            # leaves the live view. The watch _upsert path has the same
            # semantics.
            live_view = {
                k: v for k, v in content_by_object.items() if not v[2]
            }
            prev_live_view = {
                k: v for k, v in self._content_by_object.items() if not v[2]
            }
            self._content_by_object = content_by_object
            if live_view != prev_live_view:
                self._generation += 1

    def _dispatch(self, event_type: str, obj: PolicyObject) -> None:
        if event_type == "ADDED":
            self.on_add(obj)
        elif event_type == "MODIFIED":
            self.on_update(obj)
        elif event_type == "DELETED":
            self.on_delete(obj)
        elif event_type == "ERROR":
            raise WatchExpired("ERROR event from watch stream")

    # -------------------------------------------------------- event handlers

    @staticmethod
    def _is_candidate(obj: PolicyObject) -> bool:
        """True when the object carries the shadow-rollout candidate label
        (rollout/source.py CANDIDATE_LABEL): such objects are withheld
        from the live serving set and surfaced via candidate_objects()."""
        from ..rollout.source import CANDIDATE_LABEL, CANDIDATE_LABEL_VALUE

        labels = getattr(obj, "labels", None) or {}
        return labels.get(CANDIDATE_LABEL) == CANDIDATE_LABEL_VALUE

    def candidate_objects(self) -> list:
        """The current candidate-labeled Policy objects (for
        RolloutController.stage via candidate_tiers_from_objects)."""
        with self._lock:
            return list(self._candidate_objects.values())

    def _parse(self, obj: PolicyObject):
        # chaos seam: a corrupt rule turns this object's policy text into
        # garbage — the scripted poison-CRD game day (docs/resilience.md)
        content = chaos_fire("store.crd.object", obj.spec.content)
        try:
            policies = parse_policies(content, obj.name)
        except ParseError as e:
            log.error("Error parsing policy %s: %s", obj.name, e)
            quarantine_registry().quarantine("crd", obj.name, str(e))
            self._quarantined.add(obj.name)
            return None
        policies = self._validated(obj, policies)
        if policies is None:
            quarantine_registry().quarantine(
                "crd", obj.name, "rejected by strict load-time validation"
            )
            self._quarantined.add(obj.name)
            return None
        quarantine_registry().clear("crd", obj.name)
        self._quarantined.discard(obj.name)
        return policies

    def _validated(self, obj: PolicyObject, policies):
        """Apply the load-time lowerability gate to one object's policies
        per the validation mode; None rejects the object entirely."""
        if not self._validation_mode or not policies:
            return policies
        from ..analysis.loadgate import check_object_policies
        from ..apis.v1alpha1 import (
            VALIDATION_MODE_PARTIAL,
            VALIDATION_MODE_STRICT,
        )
        from ..server.metrics import record_analysis_findings

        checked = check_object_policies(policies)
        bad = [(p, f) for p, f in checked if f is not None]
        if not bad:
            return policies
        for _p, f in bad:
            record_analysis_findings(f.code, 1)
            log.log(
                logging.ERROR
                if self._validation_mode == VALIDATION_MODE_STRICT
                else logging.WARNING,
                "policy %s: analysis %s[%s]: %s",
                obj.name,
                f.severity,
                f.code,
                f.message,
            )
        if self._validation_mode == VALIDATION_MODE_STRICT:
            log.error(
                "rejecting Policy object %s (strict validation): %d "
                "policy(ies) not fastpath-lowerable",
                obj.name,
                len(bad),
            )
            return None
        if self._validation_mode == VALIDATION_MODE_PARTIAL:
            dropped = {id(p) for p, _f in bad}
            kept = [p for p in policies if id(p) not in dropped]
            log.warning(
                "Policy object %s: dropped %d of %d policy(ies) "
                "(partial validation)",
                obj.name,
                len(bad),
                len(policies),
            )
            return kept
        return policies  # permissive: annotate only

    def _copy_on_write(self, mutate) -> None:
        """Build a mutated copy and swap the reference — O(policies) per
        event (rare), O(1) per read on the authorization hot path."""
        with self._lock:
            ps = PolicySet()
            for p in self._policies.policies():
                ps.add(p, policy_id=p.policy_id)
            mutate(ps)
            self._policies = ps
            self._generation += 1

    def on_add(self, obj: PolicyObject) -> None:
        self._upsert(obj)

    def on_update(self, obj: PolicyObject) -> None:
        self._upsert(obj)

    def _upsert(self, obj: PolicyObject) -> None:
        """ADDED/MODIFIED share the semantics: replace the object's policies.
        Metadata-only MODIFIED events (same uid + content + candidate
        label state) are no-ops — no set rebuild, no generation bump, no
        recompile downstream. Candidate-labeled objects never enter the
        live set; gaining the label removes an object from it (the
        operator is pulling it into the staged corpus), losing the label
        admits it."""
        is_candidate = self._is_candidate(obj)
        key = (obj.uid, obj.spec.content, is_candidate)
        if self._content_by_object.get(obj.name) == key:
            return
        if is_candidate:
            with self._lock:
                self._candidate_objects[obj.name] = obj
            if obj.name in self._ids_by_object:
                # previously live: withdraw its policies from the set
                def mutate(ps: PolicySet) -> None:
                    for pid in self._ids_by_object.pop(obj.name, []):
                        ps.remove(pid)
                    self._content_by_object[obj.name] = key

                self._copy_on_write(mutate)
            else:
                with self._lock:
                    self._content_by_object[obj.name] = key
            return
        policies = self._parse(obj)
        if policies is None:
            return

        def mutate(ps: PolicySet) -> None:
            self._candidate_objects.pop(obj.name, None)
            for pid in self._ids_by_object.pop(obj.name, []):
                ps.remove(pid)
            ids = []
            for i, p in enumerate(policies):
                pid = f"{obj.name}{i}-{obj.uid}"
                ps.add(p, policy_id=pid)
                ids.append(pid)
            self._ids_by_object[obj.name] = ids
            self._content_by_object[obj.name] = key

        self._copy_on_write(mutate)

    def on_delete(self, obj: PolicyObject) -> None:
        quarantine_registry().clear("crd", obj.name)
        self._quarantined.discard(obj.name)
        with self._lock:
            was_candidate = (
                self._candidate_objects.pop(obj.name, None) is not None
            )
        if was_candidate and obj.name not in self._ids_by_object:
            with self._lock:
                self._content_by_object.pop(obj.name, None)
            return
        if obj.name not in self._ids_by_object:
            return  # unknown object: nothing to remove, nothing changed

        def mutate(ps: PolicySet) -> None:
            for pid in self._ids_by_object.pop(obj.name, []):
                ps.remove(pid)
            self._content_by_object.pop(obj.name, None)

        self._copy_on_write(mutate)

    # -------------------------------------------------------------- protocol

    def policy_set(self) -> PolicySet:
        # the set is immutable once published (copy-on-write swap above)
        return self._policies

    def initial_policy_load_complete(self) -> bool:
        return self._load_complete

    def name(self) -> str:
        return "CRDPolicyStore"

    def content_generation(self) -> int:
        with self._lock:
            return self._generation


# --------------------------------------------------------------- transport


class KubeAPIWatchSource:
    """Minimal list+watch client for the Policy CRD over HTTPS using a
    kubeconfig — stdlib only, via the shared KubeConfigClient transport
    (stores/kubeclient.py)."""

    def __init__(self, kubeconfig_path: str, context: str = ""):
        from .kubeclient import KubeConfigClient

        self._client = KubeConfigClient(kubeconfig_path, context)
        self.server = self._client.server
        self._resource_version = ""

    def _url(self, watch: bool = False) -> str:
        base = f"{self.server}/apis/{GROUP}/{VERSION}/policies"
        if watch:
            rv = f"&resourceVersion={self._resource_version}" if self._resource_version else ""
            return f"{base}?watch=true{rv}"
        return base

    def _open(self, url: str, timeout: Optional[float]):
        return self._client.open(url, timeout)

    def list(self) -> List[PolicyObject]:
        with self._open(self._url(), timeout=30) as resp:
            body = json.loads(resp.read())
        self._resource_version = body.get("metadata", {}).get("resourceVersion", "")
        return [PolicyObject.from_dict(item) for item in body.get("items", [])]

    def reset_resource_version(self) -> None:
        self._resource_version = ""

    def watch(self, on_event, stop) -> None:
        try:
            resp = self._open(self._url(watch=True), timeout=300)
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise WatchExpired("410 Gone") from None
            raise
        with resp:
            for line in resp:
                if stop.is_set():
                    return
                if not line.strip():
                    continue
                evt = json.loads(line)
                if evt.get("type") == "ERROR":
                    code = (evt.get("object") or {}).get("code")
                    if code == 410:
                        raise WatchExpired("410 Gone (ERROR event)")
                obj = evt.get("object", {})
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    self._resource_version = rv
                on_event(evt.get("type", ""), PolicyObject.from_dict(obj))
