"""Store configuration -> ordered tiered stores.

Behavior parity with /root/reference internal/server/store/config.go:
ParseConfig (YAML/JSON + validation) and CedarConfigStores (type switch
building the ordered store list).
"""

from __future__ import annotations

from typing import Optional

import yaml

from ..apis.v1alpha1 import (
    CedarConfig,
    STORE_TYPE_CRD,
    STORE_TYPE_DIRECTORY,
    STORE_TYPE_VERIFIED_PERMISSIONS,
)
from .avp import VerifiedPermissionsPolicyStore
from .crd import CRDPolicyStore
from .directory import DirectoryPolicyStore
from .store import TieredPolicyStores


def parse_config(data: str) -> CedarConfig:
    raw = yaml.safe_load(data)
    if raw is None:
        raw = {}
    config = CedarConfig.from_dict(raw)
    config.validate()
    return config


def cedar_config_stores(
    config: Optional[CedarConfig],
    kubeconfig_path: Optional[str] = None,
    avp_client=None,
) -> TieredPolicyStores:
    if config is None:
        return TieredPolicyStores([])
    stores = []
    for sd in config.stores:
        if sd.type == STORE_TYPE_DIRECTORY:
            stores.append(
                DirectoryPolicyStore(
                    sd.directory_store.path,
                    refresh_interval_s=sd.directory_store.refresh_interval_ns / 1e9,
                )
            )
        elif sd.type == STORE_TYPE_CRD:
            stores.append(
                CRDPolicyStore(
                    kubeconfig_path=kubeconfig_path,
                    kubeconfig_context=sd.crd_store.kubeconfig_context,
                    validation_mode=config.validation_mode,
                )
            )
        elif sd.type == STORE_TYPE_VERIFIED_PERMISSIONS:
            stores.append(
                VerifiedPermissionsPolicyStore(
                    sd.verified_permissions_store.policy_store_id,
                    client=avp_client,
                    refresh_interval_s=(
                        sd.verified_permissions_store.refresh_interval_ns / 1e9
                    ),
                    region=sd.verified_permissions_store.aws_region,
                    profile=sd.verified_permissions_store.aws_profile,
                )
            )
    return TieredPolicyStores(stores, validation_mode=config.validation_mode)


def load_config_stores(
    config_path: str, timeout_s: float = 30.0
) -> TieredPolicyStores:
    """Parse a StoreConfig file, build its tiered stores, and WAIT for
    every store's initial policy load — the one shared open/parse/poll
    helper behind the offline CLIs (cedar-replay, cedar-shadow,
    cedar-why). Raises RuntimeError when the stores are not ready within
    ``timeout_s``."""
    import time

    with open(config_path) as f:
        config = parse_config(f.read())
    stores = cedar_config_stores(config)
    deadline = time.monotonic() + timeout_s
    while not all(s.initial_policy_load_complete() for s in stores):
        if time.monotonic() > deadline:
            raise RuntimeError(f"stores not ready after {timeout_s:.0f}s")
        time.sleep(0.2)
    return stores
