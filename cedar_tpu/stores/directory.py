"""Directory policy store: loads *.cedar files, full re-read on a ticker.

Behavior parity with /root/reference internal/server/store/directory.go:
ready immediately, errors logged-and-skipped per file, policy ids namespaced
as "<filename>.policy<N>" (directory.go:75), atomic swap of the whole set.

Parse results are cached per file by content hash, so a steady-state ticker
reload of an unchanged 100k-policy directory costs file reads + hashes
(~ms) instead of a full re-parse (~40s at that scale) — the parse-once
analogue of the compiled-set hot-swap bucketing.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Dict, Optional, Tuple

from ..chaos.registry import ChaosError, chaos_fire
from ..lang.authorize import PolicySet
from ..lang.lexer import ParseError
from ..lang.parser import parse_policies
from .quarantine import quarantine_registry

log = logging.getLogger(__name__)


class DirectoryPolicyStore:
    def __init__(
        self,
        directory: str,
        refresh_interval_s: float = 60.0,
        start_ticker: bool = True,
        on_reload=None,
    ):
        self.directory = directory
        self.refresh_interval_s = refresh_interval_s
        # file names THIS store quarantined, so reload cleanup can clear
        # entries for files that vanish — including born-poison files that
        # never produced a parse-cache entry to diff against
        self._quarantined: set = set()
        self._policies = PolicySet()
        # (filename -> (content sha256, parsed policies)); entries for
        # removed files are dropped each reload
        self._parse_cache: Dict[str, Tuple[str, list]] = {}
        self._generation = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._on_reload = on_reload
        self.load_policies()
        self._ticker: Optional[threading.Thread] = None
        if start_ticker:
            self._ticker = threading.Thread(
                target=self._reload_loop, name="directory-store-reload", daemon=True
            )
            self._ticker.start()

    def close(self) -> None:
        self._stop.set()

    def _reload_loop(self) -> None:
        try:
            while not self._stop.wait(self.refresh_interval_s):
                if (
                    self._ticker is not None
                    and self._ticker is not threading.current_thread()
                ):
                    return  # superseded by revive(): a fresh ticker owns reloads
                self.load_policies()
        except BaseException:  # noqa: BLE001 — visibility, then unwind
            try:
                from ..server.metrics import record_worker_death

                record_worker_death("directory.reload")
            except Exception:  # noqa: BLE001 — must not mask the death
                pass
            log.critical(
                "directory store reload ticker died on an uncaught exception"
            )
            raise

    def ticker_threads(self) -> list:
        """The reload ticker thread(s) (supervisor liveness probe)."""
        return [self._ticker] if self._ticker is not None else []

    def revive(self, force: bool = False) -> bool:
        """Restart a dead (or, forced, wedged) reload ticker (supervisor
        hook); serving is unaffected either way — the previous policy set
        keeps answering."""
        t = self._ticker
        if self._stop.is_set() or t is None:
            return False
        if t.is_alive() and not force:
            return False
        log.warning("directory store: restarting reload ticker")
        self._ticker = threading.Thread(
            target=self._reload_loop, name="directory-store-reload", daemon=True
        )
        self._ticker.start()
        return True

    def load_policies(self) -> None:
        try:
            # chaos seam: a latency rule here is the scripted "store
            # stalls for N seconds" game day; an error rule is a reload
            # failure — both leave the previous set serving
            chaos_fire("store.load")
            entries = sorted(os.listdir(self.directory))
        except ChaosError as e:
            log.error("policy directory load failed (injected): %s", e)
            return
        except OSError as e:
            log.error("Error reading policy directory: %s", e)
            return
        ps = PolicySet()
        new_cache: Dict[str, Tuple[str, list]] = {}
        seen: set = set()
        for name in entries:
            path = os.path.join(self.directory, name)
            if not os.path.isfile(path) or not name.endswith(".cedar"):
                continue
            seen.add(name)
            try:
                with open(path, "r") as f:
                    data = f.read()
            except OSError as e:
                log.error("Error reading policy file: %s", e)
                continue
            digest = hashlib.sha256(data.encode()).hexdigest()
            cached = self._parse_cache.get(name)
            if cached is not None and cached[0] == digest:
                policies = cached[1]
            else:
                try:
                    policies = parse_policies(data, name)
                except ParseError as e:
                    log.error("Error loading policy file %s: %s", name, e)
                    quarantine_registry().quarantine("directory", name, str(e))
                    self._quarantined.add(name)
                    if cached is not None:
                        # poison-file quarantine with last-known-good
                        # retention: the file went bad on disk, but its
                        # previous parse served fine — keep serving that
                        # (under the OLD digest, so a fix is re-parsed)
                        # instead of silently dropping its policies
                        new_cache[name] = cached
                        for i, p in enumerate(cached[1]):
                            ps.add(p, policy_id=f"{name}.policy{i}")
                    continue
            quarantine_registry().clear("directory", name)
            self._quarantined.discard(name)
            new_cache[name] = (digest, policies)
            for i, p in enumerate(policies):
                ps.add(p, policy_id=f"{name}.policy{i}")
        # deleted files leave quarantine with their policies — including
        # born-poison files that never made it into the parse cache.
        # Keyed on files SEEN on disk, not the parse cache: a born-poison
        # file still present must stay quarantined.
        for name in self._quarantined - seen:
            quarantine_registry().clear("directory", name)
            self._quarantined.discard(name)
        changed = {n: d for n, (d, _) in new_cache.items()} != {
            n: d for n, (d, _) in self._parse_cache.items()
        }
        self._parse_cache = new_cache
        with self._lock:
            self._policies = ps
            if changed:
                self._generation += 1
        if self._on_reload is not None:
            self._on_reload(self)

    def policy_set(self) -> PolicySet:
        with self._lock:
            return self._policies

    def initial_policy_load_complete(self) -> bool:
        return True

    def name(self) -> str:
        return "FilePolicyStore"

    def content_generation(self) -> int:
        with self._lock:
            return self._generation
