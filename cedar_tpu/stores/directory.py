"""Directory policy store: loads *.cedar files, full re-read on a ticker.

Behavior parity with /root/reference internal/server/store/directory.go:
ready immediately, errors logged-and-skipped per file, policy ids namespaced
as "<filename>.policy<N>" (directory.go:75), atomic swap of the whole set.

Parse results are cached per file by content hash, so a steady-state ticker
reload of an unchanged 100k-policy directory costs file reads + hashes
(~ms) instead of a full re-parse (~40s at that scale) — the parse-once
analogue of the compiled-set hot-swap bucketing.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Dict, Optional, Tuple

from ..lang.authorize import PolicySet
from ..lang.lexer import ParseError
from ..lang.parser import parse_policies

log = logging.getLogger(__name__)


class DirectoryPolicyStore:
    def __init__(
        self,
        directory: str,
        refresh_interval_s: float = 60.0,
        start_ticker: bool = True,
        on_reload=None,
    ):
        self.directory = directory
        self.refresh_interval_s = refresh_interval_s
        self._policies = PolicySet()
        # (filename -> (content sha256, parsed policies)); entries for
        # removed files are dropped each reload
        self._parse_cache: Dict[str, Tuple[str, list]] = {}
        self._generation = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._on_reload = on_reload
        self.load_policies()
        self._ticker: Optional[threading.Thread] = None
        if start_ticker:
            self._ticker = threading.Thread(
                target=self._reload_loop, name="directory-store-reload", daemon=True
            )
            self._ticker.start()

    def close(self) -> None:
        self._stop.set()

    def _reload_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            self.load_policies()

    def load_policies(self) -> None:
        try:
            entries = sorted(os.listdir(self.directory))
        except OSError as e:
            log.error("Error reading policy directory: %s", e)
            return
        ps = PolicySet()
        new_cache: Dict[str, Tuple[str, list]] = {}
        for name in entries:
            path = os.path.join(self.directory, name)
            if not os.path.isfile(path) or not name.endswith(".cedar"):
                continue
            try:
                with open(path, "r") as f:
                    data = f.read()
            except OSError as e:
                log.error("Error reading policy file: %s", e)
                continue
            digest = hashlib.sha256(data.encode()).hexdigest()
            cached = self._parse_cache.get(name)
            if cached is not None and cached[0] == digest:
                policies = cached[1]
            else:
                try:
                    policies = parse_policies(data, name)
                except ParseError as e:
                    log.error("Error loading policy file %s: %s", name, e)
                    continue
            new_cache[name] = (digest, policies)
            for i, p in enumerate(policies):
                ps.add(p, policy_id=f"{name}.policy{i}")
        changed = {n: d for n, (d, _) in new_cache.items()} != {
            n: d for n, (d, _) in self._parse_cache.items()
        }
        self._parse_cache = new_cache
        with self._lock:
            self._policies = ps
            if changed:
                self._generation += 1
        if self._on_reload is not None:
            self._on_reload(self)

    def policy_set(self) -> PolicySet:
        with self._lock:
            return self._policies

    def initial_policy_load_complete(self) -> bool:
        return True

    def name(self) -> str:
        return "FilePolicyStore"

    def content_generation(self) -> int:
        with self._lock:
            return self._generation
