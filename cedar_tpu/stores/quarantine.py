"""Poison-object quarantine: last-known-good retention bookkeeping.

A policy object (CRD Policy, directory *.cedar file) that stops parsing —
or fails the load-time analysis gate — must degrade, not wedge: the store
keeps serving the object's previous good content (or drops only that
object) and records the poison here so operators can see exactly WHAT is
quarantined and WHY on ``/debug/quarantine`` (and alert on the
``cedar_quarantined_objects`` gauge) instead of diffing reload logs.

One module-level registry serves the whole process: stores quarantine and
clear under (component, object name) keys, the HTTP debug endpoint reads a
snapshot. Entries clear automatically when the object loads cleanly again
(or is deleted)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


class QuarantineRegistry:
    def __init__(self, clock=time.time):
        self._items: dict = {}  # (component, name) -> entry dict
        self._lock = threading.Lock()
        self._clock = clock

    def quarantine(self, component: str, name: str, reason: str) -> None:
        """Record (or refresh) one poisoned object. ``reason`` is the
        parse/gate error text, truncated for the debug payload."""
        with self._lock:
            entry = self._items.get((component, name))
            if entry is None:
                entry = {
                    "component": component,
                    "name": name,
                    "since_unix": round(self._clock(), 3),
                    "failures": 0,
                }
                self._items[(component, name)] = entry
                log.error(
                    "quarantined %s object %r: %s", component, name, reason
                )
            entry["failures"] += 1
            entry["reason"] = str(reason)[:500]
        self._publish()

    def clear(self, component: str, name: str) -> bool:
        """Remove one object from quarantine (it loaded cleanly or was
        deleted); True when it was quarantined."""
        with self._lock:
            entry = self._items.pop((component, name), None)
        if entry is not None:
            log.warning(
                "cleared quarantine for %s object %r", component, name
            )
            self._publish()
        return entry is not None

    def is_quarantined(self, component: str, name: str) -> bool:
        with self._lock:
            return (component, name) in self._items

    def count(self) -> int:
        with self._lock:
            return len(self._items)

    def snapshot(self) -> dict:
        """/debug/quarantine payload: every quarantined object, newest
        failure last."""
        with self._lock:
            items = [dict(e) for e in self._items.values()]
        items.sort(key=lambda e: e["since_unix"])
        return {"count": len(items), "objects": items}

    def reset(self) -> None:
        """Drop everything (tests)."""
        with self._lock:
            self._items.clear()
        self._publish()

    def _publish(self) -> None:
        try:
            from ..server.metrics import set_quarantined_objects

            set_quarantined_objects(self.count())
        except Exception:  # noqa: BLE001 — metrics must never break a load
            log.debug("quarantine gauge publish failed", exc_info=True)


_default: Optional[QuarantineRegistry] = None
_default_lock = threading.Lock()


def quarantine_registry() -> QuarantineRegistry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = QuarantineRegistry()
    return _default
