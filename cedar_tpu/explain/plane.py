"""The lazily-compiled device explain plane.

One ``ExplainPlane`` wraps a ``TPUPolicyEngine`` and answers explain
requests with the standalone bits kernel (``match_bits_arrays``, fixed
``_BITS_CHUNK`` shape, XLA plane only): its per-rule satisfaction bitset
is a superset of every other attribution payload — complete per-group
policy sets AND the winning rule — so one launch carries the whole
explanation. The ``want_full`` first/last plane (which serves
fallback-set evaluation) is deliberately NOT launched here: everything
it reports derives from the bitset, and a second dispatch would only
double the first-explain compile cost. Engine-level want_full routing
(never the fused pallas words kernel — it emits only packed words, with
nothing to attribute from) stays pinned by tests/test_pallas_match.py.

STRICTLY PAY-FOR-USE: nothing here compiles until the first explain
request per (engine, compiled set). The serving warm ladder pre-compiles
the bits shape for its own flagged-row fetches, so the first
``?explain=1`` pays at most one fresh trace — and the non-explain path
pays nothing, ever (trace-counter-asserted by tests/test_explain.py).
Fresh explain-plane traces are counted on
``cedar_explain_compiles_total``.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


class ExplainPlane:
    """Per-engine explain dispatch with lazy compile accounting."""

    def __init__(self, engine):
        self.engine = engine

    def explain_row(
        self, codes_arr: np.ndarray, extras_arr: np.ndarray, cs=None
    ) -> np.ndarray:
        """Rule-satisfaction bitsets [n, R/32] uint32 for pre-encoded
        rows — one bits fetch through the engine's existing entry point
        (bucketed to the fixed bits-chunk shape, snapshot-pinned via
        ``cs``)."""
        from ..ops.match import kernel_trace_count

        engine = self.engine
        cs = cs or engine._compiled
        if cs is None:
            raise RuntimeError("ExplainPlane: no policy set loaded")
        tc0 = kernel_trace_count()
        bits = engine.match_bits_arrays(codes_arr, extras_arr, cs=cs)
        traces = kernel_trace_count() - tc0
        if traces:
            # first use per (engine, compiled set) is exactly when fresh
            # traces appear; a warm jit cache (same-bucket reload, or the
            # serving ladder's own bits warm-up) makes the "lazy compile"
            # genuinely free and counts nothing
            try:
                from ..server.metrics import record_explain_compiles

                record_explain_compiles(traces)
            except Exception:  # noqa: BLE001 — metrics never break explain
                pass
        return bits


def encode_single(engine, cs, entities, request) -> Optional[tuple]:
    """One request through the Python encoder into the engine's bucketed
    (codes [1, S], extras [1, E]) arrays — the explain plane's encode
    (exact semantics: hard literals host-evaluated, same activation
    table as the serving engine path)."""
    from ..compiler.table import encode_request_codes

    packed = cs.packed
    encoded = encode_request_codes(packed.plan, packed.table, entities, request)
    return engine._encode_batch_arrays(cs, [encoded], 1)
