"""IR-backed decision attribution: winning rules -> policies, clauses,
and attribute tests with source spans.

The compiled clause IR already knows which clause decided every request —
``compiler.pack`` retains a per-rule back-map (``PackedPolicySet.
rule_clause``) from each packed rule column to the (policy, clause
ordinal, literal tests) it lowered from. This module turns a per-rule
satisfaction vector into an operator-facing explanation:

  * ``host_sat`` computes the satisfaction vector ON HOST with numpy from
    the Python encoder's (codes, extras) — the exact semantics of the
    device kernel (lit-vector @ W >= thresh over the same activation
    table), so breaker-open and engine-less callers still explain without
    a device launch;
  * ``sat_from_bits`` decodes the device bits plane
    (``match_rules_codes_bits``) into the same vector — the explain plane
    (plane.py) fetches it with one fixed-shape call;
  * ``build_explanation`` walks tiers over the satisfied groups (merging
    interpreter-fallback verdicts when entities are given — the exact
    walk of ``TPUPolicyEngine._finalize_sets``), picks the determining
    policy, maps its winning rule back through ``rule_clause``, and
    renders every literal of that clause as an attribute/operator/value
    test.

Source spans: the AST retains positions per POLICY (filename, line,
column — ``lang.ast.Policy.position``), not per expression, so every
test's ``span`` anchors at its owning policy and carries a rendered
``source`` string of the test itself (docs/explainability.md).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..compiler.ir import (
    CMP,
    ENTITY_IN,
    ENTITY_IN_ANY,
    EQ,
    EQ_ENTITY,
    HARD,
    HARD_ERR,
    HARD_OK,
    HAS,
    IN_SET,
    IN_SLOT,
    IS,
    LIKE,
    SET_HAS,
    TRUE,
    TYPE_ERR,
)
from ..compiler.pack import (
    ERROR_IDX,
    FORBID_IDX,
    GROUPS_PER_TIER,
    PERMIT_IDX,
    PackedPolicySet,
)
from ..lang.authorize import ALLOW, DENY, Diagnostics, Reason
from ..lang.eval import Env, policy_matches
from ..lang.format import format_expr
from ..lang.values import EvalError

# explanation ``source`` values: which plane computed the attribution
SOURCE_DEVICE = "device"  # bits launch through the engine (plane.py)
SOURCE_HOST = "host"  # numpy matching over the retained host-side pack
SOURCE_INTERPRETER = "interpreter"  # per-policy interpreter walk (no pack)
SOURCE_GATE = "gate"  # pre-evaluation short-circuit answered


# --------------------------------------------------------------- rendering


def _render_value(vk) -> object:
    """A ``lang.values.value_key`` tuple -> a JSON-friendly display value
    (strings/longs/bools verbatim, entities as ``Type::"id"``, sets as
    sorted lists, records as dicts)."""
    if not isinstance(vk, tuple) or not vk:
        return vk
    tag = vk[0]
    if tag in ("s", "l", "b"):
        return vk[1]
    if tag == "e":
        return f'{vk[1]}::"{vk[2]}"'
    if tag == "S":
        return [_render_value(x) for x in vk[1]]
    if tag == "R":
        return {k: _render_value(x) for k, x in vk[1]}
    if tag == "d":
        return f"decimal({vk[1]})"
    if tag == "i":
        return f"{vk[1]}/{vk[2]}"
    return str(vk)


def _fmt_value(v) -> str:
    """Display value -> cedar-ish source text."""
    if isinstance(v, str):
        # entity renderings already carry their own quotes
        return v if "::" in v else f'"{v}"'
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return "[" + ", ".join(_fmt_value(x) for x in v) + "]"
    return str(v)


def _slot_attr(slot) -> str:
    var, path = slot
    return ".".join((var,) + tuple(path))


def _uid_str(data) -> str:
    t, i = data
    return f'{t}::"{i}"'


# value_key tag byte -> operator-facing Cedar type name (TYPE_ERR tests)
_TAG_NAMES = {
    "s": "string", "l": "long", "b": "bool", "S": "set",
    "R": "record", "e": "entity", "d": "decimal", "i": "ipaddr",
}


def literal_test(cl) -> dict:
    """One ClauseLit -> {"attribute", "operator", "value", "negated",
    "source"}: the operator-facing rendering of one attribute test of a
    winning clause. Every positive test of a matched clause held on the
    request; every negated one provably did not."""
    lit = cl.lit
    kind = lit.kind
    attribute = _slot_attr(lit.slot) if lit.slot is not None else lit.var
    operator: str = kind
    value: object = None
    if kind == EQ:
        operator = "=="
        value = _render_value(lit.data)
    elif kind == HAS:
        operator = "has"
    elif kind == LIKE:
        operator = "like"
        value = lit.data
    elif kind == CMP:
        operator, value = lit.data
    elif kind == IN_SET:
        operator = "in"
        value = sorted(
            (_render_value(vk) for vk in lit.data), key=str
        )
    elif kind == SET_HAS:
        operator = "contains"
        value = _render_value(lit.data)
    elif kind == IS:
        operator = "is"
        value = lit.data
    elif kind == EQ_ENTITY:
        operator = "=="
        value = _uid_str(lit.data)
    elif kind == ENTITY_IN:
        operator = "in"
        value = _uid_str(lit.data)
    elif kind == ENTITY_IN_ANY:
        operator = "in"
        value = [_uid_str(u) for u in lit.data]
    elif kind == IN_SLOT:
        # ancestor-closure `in` over an attribute-chain entity value
        operator = "in"
        value = sorted(_uid_str(u) for u in lit.data)
    elif kind == TYPE_ERR:
        # positive: a Cedar type error was detected (the slot's runtime
        # value tag differs from what the typed operation needs);
        # negated: the guard proving the operand had the right type
        operator = "type-error"
        value = _TAG_NAMES.get(lit.data, lit.data)
    elif kind in (HARD, HARD_OK, HARD_ERR):
        operator = {
            HARD: "expr",
            HARD_OK: "expr-evaluates",
            HARD_ERR: "expr-errors",
        }[kind]
        value = format_expr(lit.expr) if lit.expr is not None else None
        attribute = attribute or "expr"
    elif kind == TRUE:
        operator = "true"
    if kind == HAS:
        src = f"{attribute} has"
    elif value is None:
        src = f"{attribute} {operator}"
    elif kind in (HARD, HARD_OK, HARD_ERR):
        src = str(value)
    else:
        src = f"{attribute} {operator} {_fmt_value(value)}"
    if cl.negated:
        src = f"!({src})"
    return {
        "attribute": attribute,
        "operator": operator,
        "value": value,
        "negated": bool(cl.negated),
        "source": src,
    }


def clause_tests(clause) -> List[dict]:
    return [literal_test(cl) for cl in clause]


def policy_span(filename: str, position) -> dict:
    off, line, col = position
    return {"file": filename, "line": line, "column": col, "offset": off}


# ------------------------------------------------------------ satisfaction


def host_sat(
    packed: PackedPolicySet, codes, extras
) -> np.ndarray:
    """Per-rule satisfaction vector [n_rules] bool, computed ON HOST from
    the Python encoder's (codes, extras) for one request — numpy twin of
    the device kernel (same activation table, same W/thresh), so the
    attribution is byte-equal to what the bits plane would report."""
    L = packed.L
    rows = packed.table.rows  # [V, L] uint8
    lit = np.zeros((L,), dtype=np.int32)
    for c in codes:
        c = int(c)
        if c:
            lit |= rows[c].astype(np.int32)
    for e in extras:
        e = int(e)
        if 0 <= e < L:
            lit[e] = 1
    scores = lit @ packed.W.astype(np.int32)  # [R]
    sat = scores.astype(np.float64) >= packed.thresh
    return sat[: packed.n_rules]


def sat_from_bits(packed: PackedPolicySet, bits_row, col_map=None) -> np.ndarray:
    """One device rule-bitset row ([R/32] uint32) -> [n_rules] bool.

    ``col_map`` translates shard-partitioned MESH layouts (the engine's
    compiled set carries it); decoding is shared with the engine's
    diagnostics via parallel/mesh.py bits_rule_indices — the one decoder
    of the partitioned wire format."""
    from ..parallel.mesh import bits_rule_indices

    sat = np.zeros((packed.n_rules,), dtype=bool)
    sat[bits_rule_indices(bits_row, col_map, packed.n_rules)] = True
    return sat


def _groups_from_sat(packed: PackedPolicySet, sat: np.ndarray) -> dict:
    """{group: sorted [policy index]} over the satisfied rules (deduped
    across a policy's several DNF rules) — the host twin of
    ``TPUPolicyEngine._bits_groups``."""
    idx = np.nonzero(sat)[0]
    out: dict = {}
    for r in idx.tolist():
        rc = packed.rule_clause[r]
        if rc.pm_idx < 0:
            continue  # gate rules carry no policy
        out.setdefault(rc.group, set()).add(rc.pm_idx)
    return {g: sorted(s) for g, s in out.items()}


# ----------------------------------------------------- fallback evaluation


def fallback_outcomes(
    packed: PackedPolicySet, entities, request
) -> Tuple[list, list, list]:
    """Interpreter verdicts for the pack's fallback policies, per tier:
    (allow [tier][(fp, Reason)], deny [tier][(fp, Reason)],
    errors [tier][(fp, message)]) — the merge input of the host tier
    walk, mirroring ``TPUPolicyEngine._finalize_sets``."""
    T = packed.n_tiers
    fb_allow: list = [[] for _ in range(T)]
    fb_deny: list = [[] for _ in range(T)]
    fb_errors: list = [[] for _ in range(T)]
    if packed.fallback and entities is not None:
        env = Env(request, entities)
        for fp in packed.fallback:
            p = fp.policy
            try:
                if not policy_matches(p, env):
                    continue
            except EvalError as e:
                fb_errors[fp.tier].append(
                    (fp, f"while evaluating policy `{p.policy_id}`: {e}")
                )
                continue
            reason = Reason(p.policy_id, p.filename, p.position)
            (fb_deny if p.effect == "forbid" else fb_allow)[fp.tier].append(
                (fp, reason)
            )
    return fb_allow, fb_deny, fb_errors


# --------------------------------------------------------------- tier walk


def _clause_counts(packed: PackedPolicySet) -> dict:
    """{(pm_idx, group): clause count} in ONE rule_clause pass — the
    "clause N of M" denominators, computed once per explanation instead
    of an O(R) rescan per winning-policy doc."""
    counts: dict = {}
    for rc in packed.rule_clause:
        key = (rc.pm_idx, rc.group)
        counts[key] = counts.get(key, 0) + 1
    return counts


def _clause_doc(
    packed: PackedPolicySet, pm_idx: int, group: int, sat, counts: dict
) -> Optional[dict]:
    """The winning clause for (policy, group): the LOWEST satisfied rule
    column belonging to it (pack sorts rules by (group, policy), and a
    policy's clauses keep source order within that run), rendered with its
    ordinal and attribute tests. The scan is over SATISFIED rules only
    (a handful); the denominators come from the precomputed counts."""
    win = None
    for r in np.nonzero(sat)[0].tolist():
        rc = packed.rule_clause[r]
        if rc.pm_idx == pm_idx and rc.group == group:
            win = rc
            break
    if win is None or win.clause is None:
        return None
    return {
        "index": win.ordinal,
        "of": counts.get((pm_idx, group), 1),
        "kind": win.kind,
        "tests": clause_tests(win.clause),
    }


def _policy_doc(
    packed: PackedPolicySet, pm_idx: int, group: int, sat, counts: dict
) -> dict:
    meta = packed.policy_meta[pm_idx]
    return {
        "policyId": meta.policy_id,
        "effect": meta.effect,
        "tier": meta.tier,
        "span": policy_span(meta.filename, meta.position),
        "fallback": False,
        "clause": _clause_doc(packed, pm_idx, group, sat, counts),
    }


def _fallback_doc(fp) -> dict:
    p = fp.policy
    return {
        "policyId": p.policy_id,
        "effect": p.effect,
        "tier": fp.tier,
        "span": policy_span(p.filename, p.position),
        "fallback": True,
        "clause": None,
        "unlowerable": {"code": fp.code, "reason": fp.reason},
    }


def build_explanation(
    packed: PackedPolicySet,
    sat: np.ndarray,
    entities=None,
    request=None,
    source: str = SOURCE_HOST,
) -> Tuple[str, Diagnostics, dict]:
    """(cedar decision, Diagnostics, explanation) from one request's rule
    satisfaction vector. The Diagnostics mirror the serving paths'
    ``_finalize_sets`` output exactly (device reasons ascending by policy
    index, then fallback reasons in pack order), so a caller mapping them
    through ``CedarWebhookAuthorizer._map_verdict`` renders the same
    reason bytes the non-explain path would."""
    groups = _groups_from_sat(packed, sat)
    fb_allow, fb_deny, fb_errors = fallback_outcomes(
        packed, entities, request
    )
    counts = _clause_counts(packed)
    T = packed.n_tiers
    for t in range(T):
        base = t * GROUPS_PER_TIER
        deny = [
            ("device", i, base + FORBID_IDX)
            for i in groups.get(base + FORBID_IDX, ())
        ] + [("fallback", fp, None) for fp, _r in fb_deny[t]]
        allow = [
            ("device", i, base + PERMIT_IDX)
            for i in groups.get(base + PERMIT_IDX, ())
        ] + [("fallback", fp, None) for fp, _r in fb_allow[t]]
        err_pols = [
            ("device", i, base + ERROR_IDX)
            for i in groups.get(base + ERROR_IDX, ())
        ] + [("fallback", fp, None) for fp, _m in fb_errors[t]]
        errors = [
            f"while evaluating policy "
            f"`{packed.policy_meta[i].policy_id}`: evaluation error"
            for i in groups.get(base + ERROR_IDX, ())
        ] + [m for _fp, m in fb_errors[t]]
        winners = deny or allow
        if winners:
            decision = DENY if deny else ALLOW
            reasons = []
            for kind, who, _g in winners:
                if kind == "device":
                    m = packed.policy_meta[who]
                    reasons.append(Reason(m.policy_id, m.filename, m.position))
                else:
                    p = who.policy
                    reasons.append(Reason(p.policy_id, p.filename, p.position))
            docs = [
                _policy_doc(packed, who, g, sat, counts)
                if kind == "device"
                else _fallback_doc(who)
                for kind, who, g in winners
            ]
            det = docs[0]
            return (
                decision,
                Diagnostics(reasons=reasons, errors=errors),
                {
                    "decision": decision,
                    "tier": t,
                    "source": source,
                    "fallback": bool(det.get("fallback")),
                    "determining": det,
                    "reasons": docs,
                    "errors": errors,
                },
            )
        if errors:
            docs = [
                _policy_doc(packed, who, g, sat, counts)
                if kind == "device"
                else _fallback_doc(who)
                for kind, who, g in err_pols
            ]
            det = docs[0] if docs else None
            return (
                DENY,
                Diagnostics(reasons=[], errors=errors),
                {
                    "decision": DENY,
                    "tier": t,
                    "source": source,
                    "fallback": bool(det and det.get("fallback")),
                    "determining": det,
                    "reasons": [],
                    "errors": errors,
                },
            )
    return (
        DENY,
        Diagnostics(),
        {
            "decision": DENY,
            "tier": None,
            "source": source,
            "fallback": False,
            "determining": None,
            "reasons": [],
            "errors": [],
        },
    )


# ------------------------------------------------------- interpreter walk


def _reason_policy(ps, r, request):
    """Resolve a reason's Policy on sets where ids may legally collide
    across tenants (tenancy's FusedPolicySet — per-tenant directory
    stores commonly carry the same bare-filename ids): prefer the policy
    whose fused tenant matches the request's stamped ``context.tenantId``
    (a foreign clone's effect would mis-attribute the decision), then an
    exact source-span match, then the first id match."""
    want = None
    try:
        from ..compiler.pack import TENANT_CONTEXT_KEY

        want = request.context.attrs.get(TENANT_CONTEXT_KEY)
    except Exception:  # noqa: BLE001 — single-tenant shapes
        want = None
    first = span = None
    for p in ps.policies():
        if p.policy_id != r.policy:
            continue
        t = p.__dict__.get("_cedar_tenant")
        if want is not None and t == want:
            return p
        if p.filename == r.filename and p.position == r.position:
            span = span or p
        first = first or p
    return span or first


def interpreter_explanation(
    tiers, entities, request
) -> Tuple[str, Diagnostics, dict]:
    """Host-computed explanation with NO compiled pack at all: walk the
    tiers with the interpreter (``PolicySet.is_authorized`` semantics —
    first tier with any explicit signal wins), attributing the decision to
    the first reason's policy. Clause-level attribution needs the lowered
    IR, so ``clause`` is null here; the policy id, effect, tier and span
    are exact."""
    for t, ps in enumerate(tiers):
        decision, diag = ps.is_authorized(entities, request)
        if diag.reasons or diag.errors:
            docs = []
            for r in diag.reasons:
                p = _reason_policy(ps, r, request)
                docs.append(
                    {
                        "policyId": r.policy,
                        "effect": getattr(p, "effect", None),
                        "tier": t,
                        "span": policy_span(r.filename, r.position),
                        "fallback": False,
                        "clause": None,
                    }
                )
            det = docs[0] if docs else None
            return (
                decision,
                diag,
                {
                    "decision": decision,
                    "tier": t,
                    "source": SOURCE_INTERPRETER,
                    "fallback": False,
                    "determining": det,
                    "reasons": docs,
                    "errors": list(diag.errors),
                },
            )
    return (
        DENY,
        Diagnostics(),
        {
            "decision": DENY,
            "tier": None,
            "source": SOURCE_INTERPRETER,
            "fallback": False,
            "determining": None,
            "reasons": [],
            "errors": [],
        },
    )


def attribution_summary(explanation: dict) -> dict:
    """The compact exemplar attribution for rollout diff reports: just
    enough to say WHY a decision flipped (determining policy, effect,
    tier, clause ordinal, source) without the full test payload."""
    det = explanation.get("determining") or {}
    clause = det.get("clause") or {}
    return {
        "decision": explanation.get("decision"),
        "policyId": det.get("policyId"),
        "effect": det.get("effect"),
        "tier": explanation.get("tier"),
        "clause": clause.get("index"),
        "fallback": bool(explanation.get("fallback")),
        "source": explanation.get("source"),
    }
