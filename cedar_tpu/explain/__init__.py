"""Decision explainability plane (docs/explainability.md).

``?explain=1`` on /v1/authorize and /v1/admit, the ``cedar-why`` replay
CLI, and rollout diff attribution all answer through this package: the
compiled clause IR's per-rule back-map (``compiler.pack
PackedPolicySet.rule_clause``) turns winning rule indices into the
determining policy, its clause, and the matched attribute tests with
source spans. Strictly pay-for-use — importing the serving stack never
imports this package; the device explain shapes compile on first use per
(engine, compiled set).
"""

from .attribution import (
    SOURCE_DEVICE,
    SOURCE_GATE,
    SOURCE_HOST,
    SOURCE_INTERPRETER,
    attribution_summary,
    build_explanation,
    clause_tests,
    host_sat,
    interpreter_explanation,
    literal_test,
    sat_from_bits,
)
from .explainer import DiffAttributor, Explainer, engine_of
from .plane import ExplainPlane

__all__ = [
    "SOURCE_DEVICE",
    "SOURCE_GATE",
    "SOURCE_HOST",
    "SOURCE_INTERPRETER",
    "DiffAttributor",
    "ExplainPlane",
    "Explainer",
    "attribution_summary",
    "build_explanation",
    "clause_tests",
    "engine_of",
    "host_sat",
    "interpreter_explanation",
    "literal_test",
    "sat_from_bits",
]
