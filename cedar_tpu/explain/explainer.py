"""Request-level explanation for both webhooks, plus the rollout diff
attributor.

``Explainer`` answers ``?explain=1`` requests end to end: it re-derives
the decision through an attribution-capable plane AND renders why —
determining policy id, effect, clause, per-test attribute/operator/value
with source spans, the tier, and whether the interpreter fallback
answered. Three planes, tried in order per path:

  * DEVICE — engine loaded and (when one is wired) the circuit breaker
    closed: the lazily-compiled explain plane (plane.py; ``want_full``
    launch + bits fetch);
  * HOST — an engine holds a compiled set but the device must not be
    touched (breaker open) or the device launch failed: numpy matching
    over the retained host-side pack — same tables, same semantics, no
    device call;
  * INTERPRETER — no compiled set at all (interpreter deployments):
    per-tier interpreter walk; policy-level attribution, no clause tests.

Every plane merges interpreter-fallback policy verdicts exactly like the
serving engine's host tier walk, so the explained decision and reason
bytes match what the non-explain path answers for the same request.

Explain requests deliberately BYPASS the decision cache (never read,
never populate — cached entries carry no clause indices), the rollout
shadow offer, and the error injector: this is an operator debugging
surface, not serving traffic (docs/explainability.md).
"""

from __future__ import annotations

import json
import logging
from typing import Optional, Tuple

from ..lang.authorize import Diagnostics
from .attribution import (
    SOURCE_DEVICE,
    SOURCE_GATE,
    SOURCE_HOST,
    attribution_summary,
    build_explanation,
    host_sat,
    interpreter_explanation,
    sat_from_bits,
)
from .plane import ExplainPlane, encode_single

log = logging.getLogger(__name__)


def engine_of(evaluate) -> Optional[object]:
    """The TPUPolicyEngine behind a bound ``evaluate`` callable, if any —
    lets the webhook server find the engine on stacks wired through
    ``CedarWebhookAuthorizer(evaluate=engine.evaluate)`` without a fast
    path."""
    from ..engine.evaluator import TPUPolicyEngine

    owner = getattr(evaluate, "__self__", None)
    return owner if isinstance(owner, TPUPolicyEngine) else None


def _gate_explanation(label: str, **extra) -> dict:
    doc = {
        "decision": None,
        "tier": None,
        "source": SOURCE_GATE,
        "fallback": False,
        "determining": None,
        "reasons": [],
        "errors": [],
        "shortCircuit": label,
    }
    doc.update(extra)
    return doc


class Explainer:
    """Explanation engine for one server's authorization + admission
    stacks. Construction is cheap (no compiles, no device access); all
    device work happens lazily inside the per-engine ExplainPlane."""

    def __init__(
        self,
        authorizer=None,
        admission_handler=None,
        authz_engine=None,
        admission_engine=None,
        authz_breaker=None,
        admission_breaker=None,
        authz_packed=None,
        admission_packed=None,
    ):
        self.authorizer = authorizer
        self.admission_handler = admission_handler
        self._engines = {
            "authorization": (authz_engine, authz_breaker, authz_packed),
            "admission": (admission_engine, admission_breaker, admission_packed),
        }
        self._planes: dict = {}

    # ------------------------------------------------------------ plumbing

    def _plane(self, engine) -> ExplainPlane:
        plane = self._planes.get(id(engine))
        if plane is None:
            plane = self._planes[id(engine)] = ExplainPlane(engine)
        return plane

    def _interpreter_tiers(self, path: str) -> list:
        stack = (
            self.authorizer if path == "authorization" else self.admission_handler
        )
        stores = getattr(stack, "stores", None)
        if stores is None:
            return []
        return [s.policy_set() for s in stores]

    def _explain_eval(
        self, path: str, entities, request
    ) -> Tuple[str, Diagnostics, dict]:
        """(cedar decision, Diagnostics, explanation) through the best
        available plane for ``path``."""
        engine, breaker, packed_override = self._engines[path]
        cs = engine._compiled if engine is not None else None
        if cs is not None and (breaker is None or breaker.allow()):
            try:
                codes_arr, extras_arr = encode_single(
                    engine, cs, entities, request
                )
                bits = self._plane(engine).explain_row(
                    codes_arr, extras_arr, cs=cs
                )
                sat = sat_from_bits(
                    cs.packed, bits[0], getattr(cs, "col_map", None)
                )
                return build_explanation(
                    cs.packed, sat, entities, request, source=SOURCE_DEVICE
                )
            except Exception:  # noqa: BLE001 — the host plane still answers
                log.exception(
                    "device explain failed (%s); host attribution", path
                )
        packed = cs.packed if cs is not None else packed_override
        if packed is not None:
            from ..compiler.table import encode_request_codes

            codes, extras = encode_request_codes(
                packed.plan, packed.table, entities, request
            )
            sat = host_sat(packed, codes, extras)
            return build_explanation(
                packed, sat, entities, request, source=SOURCE_HOST
            )
        return interpreter_explanation(
            self._interpreter_tiers(path), entities, request
        )

    # ----------------------------------------------------- authorization

    def explain_authorize(self, body: bytes):
        """(decision, reason, error, explanation) for one raw SAR body —
        decision/reason/error carry the exact webhook answer semantics of
        the uncached python path; explanation is the ``?explain`` payload."""
        from ..server.authorizer import (
            CedarWebhookAuthorizer,
            DECISION_NO_OPINION,
        )
        from ..server.http import get_authorizer_attributes

        try:
            sar = json.loads(body)
            attributes = get_authorizer_attributes(sar)
            # tenant stamp (cedar_tpu/tenancy): on a fused plane the
            # explain answer must evaluate under the same context.tenantId
            # the serving paths stamp, or every tenant-guarded policy
            # fails its guard and explain contradicts the served decision
            attributes.tenant = getattr(body, "tenant", "")
        except Exception as e:  # noqa: BLE001 — mirror the decode-error answer
            return (
                DECISION_NO_OPINION,
                "Encountered decoding error",
                f"failed parsing request body: {e}",
                _gate_explanation("decode-error", error=str(e)),
            )
        if self.authorizer is not None:
            # labeled at the gate itself (authorizer._short_circuit_labeled)
            # so this surface can never mislabel a gate it only saw the
            # (decision, reason) of
            short = self.authorizer._short_circuit_labeled(attributes)
            if short is not None:
                decision, reason, label = short
                return decision, reason, None, _gate_explanation(label)
        try:
            from ..server.authorizer import record_to_cedar_resource

            entities, request = record_to_cedar_resource(attributes)
            decision, diag, explanation = self._explain_eval(
                "authorization", entities, request
            )
        except Exception as e:  # noqa: BLE001 — always answer the operator
            log.exception("explain authorize failed")
            return (
                DECISION_NO_OPINION,
                "",
                f"evaluation error: {e}",
                _gate_explanation("explain-error", error=str(e)),
            )
        mapped, reason = CedarWebhookAuthorizer._map_verdict(decision, diag)
        explanation["webhookDecision"] = mapped
        return mapped, reason, None, explanation

    # --------------------------------------------------------- admission

    def explain_admit(self, body: bytes):
        """(AdmissionResponse, explanation) for one raw AdmissionReview
        body, mirroring the handler's gates and response rendering."""
        from ..entities.admission import AdmissionRequest
        from ..server.admission import SKIPPED_NAMESPACES, AdmissionResponse

        handler = self.admission_handler
        try:
            review = json.loads(body)
        except (ValueError, TypeError, RecursionError) as e:
            return (
                AdmissionResponse(
                    uid="", allowed=False, code=400,
                    error=f"failed parsing body: {e}",
                ),
                _gate_explanation("decode-error", error=str(e)),
            )
        try:
            req = AdmissionRequest.from_admission_review(review)
        except Exception as e:  # noqa: BLE001 — fail-open like the handler
            allowed = bool(getattr(handler, "allow_on_error", True))
            return (
                AdmissionResponse(
                    uid="", allowed=allowed, code=200,
                    error=f"evaluation error "
                    f"({'allowed' if allowed else 'denied'} on error): {e}",
                ),
                _gate_explanation("conversion-error", error=str(e)),
            )
        if req.namespace in SKIPPED_NAMESPACES:
            return (
                AdmissionResponse(uid=req.uid, allowed=True),
                _gate_explanation("namespace-skip"),
            )
        if handler is not None and not handler._ready():
            return (
                AdmissionResponse(uid=req.uid, allowed=True),
                _gate_explanation("stores-not-ready"),
            )
        try:
            # tenant stamp (cedar_tpu/tenancy): same contract as
            # explain_authorize — evaluate under the request's tenant
            req.tenant = getattr(body, "tenant", "")
            entities, cedar_req = handler._build(req)
            decision, diag, explanation = self._explain_eval(
                "admission", entities, cedar_req
            )
        except Exception as e:  # noqa: BLE001 — mirror the handler's 500
            log.exception("explain admit failed")
            return (
                AdmissionResponse(
                    uid=req.uid,
                    allowed=bool(getattr(handler, "allow_on_error", True)),
                    code=500,
                    error=str(e),
                ),
                _gate_explanation("explain-error", error=str(e)),
            )
        response = handler._decide(req, decision, diag)
        explanation["webhookDecision"] = (
            "allow" if response.allowed else "deny"
        )
        return response, explanation


class DiffAttributor:
    """Determining-policy attribution for rollout diff exemplars: on a
    decision flip, explain the SAME request against the live and the
    candidate packs so the report says which policy (and clause) decided
    each side. Host-plane only — the shadow worker must never launch
    device work (it would steal the serving engine's device and perturb
    the trace-counter guarantees); engines without a compiled set fall
    back to the interpreter walk over the candidate's store tiers."""

    def __init__(
        self,
        live_authz_engine=None,
        live_admission_engine=None,
        candidate=None,
        live_authz_tiers=None,
        live_admission_tiers=None,
    ):
        self.live_authz = live_authz_engine
        self.live_admission = live_admission_engine
        self.candidate = candidate
        # interpreter-walk fallbacks for the live side (offline
        # cedar-shadow replay, interpreter deployments): policy-level
        # attribution when no live engine holds a compiled pack
        self.live_authz_tiers = list(live_authz_tiers or ())
        self.live_admission_tiers = list(live_admission_tiers or ())

    @staticmethod
    def _summary(engine, tiers, entities, request) -> Optional[dict]:
        try:
            cs = engine.compiled_set if engine is not None else None
            if cs is not None:
                from ..compiler.table import encode_request_codes

                packed = cs.packed
                codes, extras = encode_request_codes(
                    packed.plan, packed.table, entities, request
                )
                sat = host_sat(packed, codes, extras)
                _d, _diag, expl = build_explanation(
                    packed, sat, entities, request, source=SOURCE_HOST
                )
                return attribution_summary(expl)
            if tiers:
                _d, _diag, expl = interpreter_explanation(
                    tiers, entities, request
                )
                return attribution_summary(expl)
        except Exception:  # noqa: BLE001 — attribution is best-effort
            log.exception("diff attribution failed")
        return None

    def authorization(self, attributes) -> Optional[dict]:
        from ..server.authorizer import record_to_cedar_resource

        try:
            entities, request = record_to_cedar_resource(attributes)
        except Exception:  # noqa: BLE001 — best-effort
            return None
        cand = self.candidate
        cand_engine = getattr(cand, "authz_engine", None)
        cand_tiers = list(getattr(cand, "tiers", ()) or ())
        out = {}
        live = self._summary(
            self.live_authz, self.live_authz_tiers, entities, request
        )
        if live is not None:
            out["live"] = live
        c = self._summary(cand_engine, cand_tiers, entities, request)
        if c is not None:
            out["candidate"] = c
        return out or None

    def admission(self, req) -> Optional[dict]:
        cand = self.candidate
        handler = getattr(cand, "admission_handler", None)
        if handler is None:
            return None
        try:
            entities, cedar_req = handler._build(req)
        except Exception:  # noqa: BLE001 — best-effort
            return None
        cand_engine = getattr(cand, "admission_engine", None)
        cand_tiers = [s.policy_set() for s in handler.stores]
        out = {}
        live = self._summary(
            self.live_admission, self.live_admission_tiers, entities, cedar_req
        )
        if live is not None:
            out["live"] = live
        c = self._summary(cand_engine, cand_tiers, entities, cedar_req)
        if c is not None:
            out["candidate"] = c
        return out or None
