"""Multi-chip sharding of the policy evaluator.

The evaluation step shards over a 2-D device mesh:

  * ``data`` axis — batch data parallelism over in-flight requests (the
    moral successor of the reference's goroutine-per-HTTP-request model,
    SURVEY.md §2.4)
  * ``policy`` axis — tensor parallelism over the rule dimension of the
    policy matrix W [L, R]: each device holds a rule shard, computes its
    shard's verdicts, and the tiny per-(tier, effect) group reductions
    all-reduce across the axis (an OR-reduction — associative, so
    shard-and-reduce is exact)

XLA inserts the collectives from sharding annotations; they ride ICI within
a slice and DCN across hosts. There is no NCCL/MPI analogue to port — the
reference has no distributed backend (SURVEY.md §2.4); this mesh IS the
distributed communication design.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match import (
    INT32_MAX,
    _lit_dtype,
    _lit_matrix_codes,
    _scores,
    _tier_walk,
    match_rules,
)


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """Build a (data, policy) mesh.

    ``shape`` is the EXPLICIT (data_parallel, policy_parallel)
    factorization — the deployment chooses it from its workload (wide
    batches want data shards; huge policy sets want rule shards). When
    omitted, every device goes to the policy axis: the rule dimension
    (R ~ policies x clauses) is the axis that outgrows one chip first,
    batch data parallelism is already amortized by micro-batching, and a
    policy-only split needs no cross-shard reduction of the request axis.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"mesh needs {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = (1, n)
    data_parallel, policy_parallel = shape
    if data_parallel * policy_parallel != n:
        raise ValueError(
            f"mesh shape {shape} needs {data_parallel * policy_parallel} "
            f"devices, have {n}"
        )
    arr = np.array(devices).reshape(data_parallel, policy_parallel)
    return Mesh(arr, ("data", "policy"))


def shard_policy_tensors(mesh: Mesh, W, thresh, rule_group, rule_policy):
    """Place the packed policy tensors with the rule axis sharded."""
    w_s = NamedSharding(mesh, P(None, "policy"))
    r_s = NamedSharding(mesh, P("policy"))
    return (
        jax.device_put(W, w_s),
        jax.device_put(thresh, r_s),
        jax.device_put(rule_group, r_s),
        jax.device_put(rule_policy, r_s),
    )


def sharded_match_fn(mesh: Mesh, n_groups: int):
    """A jitted evaluation step with explicit input/output shardings.

    Inputs: active [B, A] sharded over data; policy tensors sharded over the
    policy axis. Outputs replicated on policy (XLA inserts the all-reduce
    for the group-hit matmul and the cross-shard min for first-match)."""
    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # active
        NamedSharding(mesh, P(None, "policy")),  # W
        NamedSharding(mesh, P("policy")),  # thresh
        NamedSharding(mesh, P("policy")),  # rule_group
        NamedSharding(mesh, P("policy")),  # rule_policy
    )
    out_shardings = (
        NamedSharding(mesh, P("data", None)),  # hits
        NamedSharding(mesh, P("data", None)),  # first_policy
    )

    @functools.partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )
    def step(active, W, thresh, rule_group, rule_policy):
        return match_rules(active, W, thresh, rule_group, rule_policy, n_groups)

    return step


# --------------------------------------------------- production codes path


def shard_codes_tensors(mesh: Mesh, act_rows, W, thresh, rule_group, rule_policy):
    """Place the feature-code evaluation tensors: activation table
    replicated (every shard expands the same request features), rule axis
    sharded."""
    rep = NamedSharding(mesh, P(None, None))
    w_s = NamedSharding(mesh, P(None, "policy"))
    r_s = NamedSharding(mesh, P("policy"))
    return (
        jax.device_put(act_rows, rep),
        jax.device_put(W, w_s),
        jax.device_put(thresh, r_s),
        jax.device_put(rule_group, r_s),
        jax.device_put(rule_policy, r_s),
    )


def sharded_codes_match_fn(
    mesh: Mesh, n_tiers: int, has_gate: bool = False, donate: bool = False
):
    """The production evaluation step, sharded: feature codes in, packed
    uint32 verdict words out. This is the step TPUPolicyEngine.match_arrays
    routes through when the engine owns a mesh.

    - codes/extras shard over ``data`` (batch parallelism);
    - W [L, R] + rule tensors shard over ``policy`` (rule parallelism);
    - each shard computes its local per-(tier, effect) first/last-match
      extrema; the cross-shard combine is a min/max all-reduce XLA inserts
      from the sharding annotations — first-match is a min-reduction, so
      shard-and-reduce is exact;
    - the tier walk runs on the replicated [B, G] extrema, and the readback
      is 4 bytes per request, sharded over data.

    Returns (packed words [B], (first [B, G], last [B, G])) — the same
    surface as ops.match.match_rules_codes(want_full=True); has_gate adds
    the fallback-scope gate column and the WORD_GATE bit exactly like the
    single-device kernel.

    donate hands the per-batch codes/extras shards back to XLA as scratch
    (ops/match.py match_rules_codes_donated has the rationale); the
    engine enables it on TPU-class backends only — the CPU runtime may
    alias numpy inputs, which the engine's staging pool reuses."""
    G = n_tiers * 3 + (1 if has_gate else 0)
    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # codes [B, S]
        NamedSharding(mesh, P("data", None)),  # extras [B, E]
        NamedSharding(mesh, P(None, None)),  # act_rows [V, L]
        NamedSharding(mesh, P(None, "policy")),  # W [L, R]
        NamedSharding(mesh, P("policy")),  # thresh [R]
        NamedSharding(mesh, P("policy")),  # rule_group [R]
        NamedSharding(mesh, P("policy")),  # rule_policy [R]
    )
    out_shardings = (
        NamedSharding(mesh, P("data")),  # packed words [B]
        NamedSharding(mesh, P("data", None)),  # first [B, G]
        NamedSharding(mesh, P("data", None)),  # last [B, G]
    )

    @functools.partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(0, 1) if donate else (),
    )
    def step(codes, extras, act_rows, W, thresh, rule_group, rule_policy):
        lit = _lit_matrix_codes(
            codes, extras, act_rows, _lit_dtype(W.dtype)
        )  # [B, L]
        scores = _scores(lit, W)  # [B, R] — R sharded
        sat = scores >= thresh[None, :]
        masked_min = jnp.where(sat, rule_policy[None, :], INT32_MAX)
        masked_max = jnp.where(sat, rule_policy[None, :], -1)
        firsts = []
        lasts = []
        for g in range(G):
            in_g = (rule_group == g)[None, :]
            firsts.append(
                jnp.min(
                    jnp.where(in_g, masked_min, INT32_MAX),
                    axis=1,  # cross-shard min all-reduce over the policy axis
                )
            )
            lasts.append(
                # cross-shard max all-reduce; min != max flags multi-match
                jnp.max(jnp.where(in_g, masked_max, -1), axis=1)
            )
        first = jnp.stack(firsts, axis=1)  # [B, G] replicated on policy
        last = jnp.stack(lasts, axis=1)
        packed = _tier_walk(first, last, n_tiers)
        if has_gate:
            gate = (first[:, n_tiers * 3] != INT32_MAX).astype(jnp.uint32)
            packed = packed | (gate << 27)
        return packed, first, last

    return step


def sharded_codes_bits_fn(mesh: Mesh):
    """Sharded twin of ops.match.match_rules_codes_bits: per-rule
    satisfaction bitsets [B, R // 32] for diagnostic rendering. Each shard
    packs its contiguous rule range; the output sharding along the rule-word
    axis makes the host concatenation implicit."""
    from ..ops.match import _pack_sat_bits

    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # codes
        NamedSharding(mesh, P("data", None)),  # extras
        NamedSharding(mesh, P(None, None)),  # act_rows
        NamedSharding(mesh, P(None, "policy")),  # W
        NamedSharding(mesh, P("policy")),  # thresh
    )
    out_shardings = NamedSharding(mesh, P("data", "policy"))

    @functools.partial(
        jax.jit, in_shardings=in_shardings, out_shardings=out_shardings
    )
    def step(codes, extras, act_rows, W, thresh):
        lit = _lit_matrix_codes(codes, extras, act_rows, _lit_dtype(W.dtype))
        scores = _scores(lit, W)
        sat = scores >= thresh[None, :]
        return _pack_sat_bits(sat)

    return step
