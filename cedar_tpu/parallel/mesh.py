"""Multi-chip sharding of the policy evaluator.

The evaluation step shards over a 2-D device mesh:

  * ``data`` axis — batch data parallelism over in-flight requests (the
    moral successor of the reference's goroutine-per-HTTP-request model,
    SURVEY.md §2.4)
  * ``policy`` axis — tensor parallelism over the rule dimension of the
    policy matrix W [L, R]: each device holds a rule shard, computes its
    shard's verdicts, and the tiny per-(tier, effect) group reductions
    all-reduce across the axis (an OR-reduction — associative, so
    shard-and-reduce is exact)

XLA inserts the collectives from sharding annotations; they ride ICI within
a slice and DCN across hosts. There is no NCCL/MPI analogue to port — the
reference has no distributed backend (SURVEY.md §2.4); this mesh IS the
distributed communication design.
"""

from __future__ import annotations

import functools
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match import (
    INT32_MAX,
    _lit_dtype,
    _lit_matrix_codes,
    _scores,
    _tier_walk,
    match_rules,
)


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """Build a (data, policy) mesh.

    ``shape`` is the EXPLICIT (data_parallel, policy_parallel)
    factorization — the deployment chooses it from its workload (wide
    batches want data shards; huge policy sets want rule shards). When
    omitted, every device goes to the policy axis: the rule dimension
    (R ~ policies x clauses) is the axis that outgrows one chip first,
    batch data parallelism is already amortized by micro-batching, and a
    policy-only split needs no cross-shard reduction of the request axis.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"mesh needs {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = (1, n)
    data_parallel, policy_parallel = shape
    if data_parallel * policy_parallel != n:
        raise ValueError(
            f"mesh shape {shape} needs {data_parallel * policy_parallel} "
            f"devices, have {n}"
        )
    arr = np.array(devices).reshape(data_parallel, policy_parallel)
    return Mesh(arr, ("data", "policy"))


def mesh_is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices of more than one jax process —
    the pod regime, where placement must restrict itself to addressable
    devices and step outputs must replicate so every host can read them."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def partition_hosts(mesh: Mesh) -> Dict[int, Tuple[int, ...]]:
    """Policy-partition → owning process indexes. The pod topology
    (cedar_tpu/pod/topology.py) arranges the device grid so each policy
    column lives on exactly ONE host; this map is how placement, the
    dirty-reupload pinning, and /debug/pod all agree on who that is."""
    devs = np.asarray(mesh.devices)
    return {
        p: tuple(sorted({d.process_index for d in devs[:, p].flat}))
        for p in range(devs.shape[1])
    }


def shard_policy_tensors(mesh: Mesh, W, thresh, rule_group, rule_policy):
    """Place the packed policy tensors with the rule axis sharded."""
    w_s = NamedSharding(mesh, P(None, "policy"))
    r_s = NamedSharding(mesh, P("policy"))
    return (
        jax.device_put(W, w_s),
        jax.device_put(thresh, r_s),
        jax.device_put(rule_group, r_s),
        jax.device_put(rule_policy, r_s),
    )


def sharded_match_fn(mesh: Mesh, n_groups: int):
    """A jitted evaluation step with explicit input/output shardings.

    Inputs: active [B, A] sharded over data; policy tensors sharded over the
    policy axis. Outputs replicated on policy (XLA inserts the all-reduce
    for the group-hit matmul and the cross-shard min for first-match)."""
    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # active
        NamedSharding(mesh, P(None, "policy")),  # W
        NamedSharding(mesh, P("policy")),  # thresh
        NamedSharding(mesh, P("policy")),  # rule_group
        NamedSharding(mesh, P("policy")),  # rule_policy
    )
    out_shardings = (
        NamedSharding(mesh, P("data", None)),  # hits
        NamedSharding(mesh, P("data", None)),  # first_policy
    )

    @functools.partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )
    def step(active, W, thresh, rule_group, rule_policy):
        return match_rules(active, W, thresh, rule_group, rule_policy, n_groups)

    return step


# ------------------------------------------------ shard-partitioned planes

# H2D placement transfer counter: every single-device upload the
# partitioned placement performs (and every replicated re-place) bumps it,
# so a test can pin "a one-policy edit re-places exactly ONE partition"
# the same way trace counters pin compile-free swaps.
_placement_transfers = 0
_placement_lock = threading.Lock()


def placement_transfer_count() -> int:
    """Monotonic count of per-device H2D uploads performed by
    PartitionedPlanes (diff across an operation to measure it)."""
    with _placement_lock:
        return _placement_transfers


class MeshCapacityError(ValueError):
    """The rule set does not fit the per-device packed capacity: one
    partition's column count exceeds max_rules_per_partition. The fix is
    more devices on the policy axis (or a higher capacity budget) — the
    whole point of rule-axis sharding is that capacity scales with
    device count."""


def bits_rule_indices(
    bits_row: np.ndarray, col_map: Optional[np.ndarray], n_rules: int
) -> np.ndarray:
    """Set-bit positions of one device rule-bitset row as PACKED rule
    indices — the ONE decoder of the partitioned wire format, shared by
    the engine's diagnostics (_bits_groups) and the explain plane
    (sat_from_bits) so the two can never drift from the layout this
    module defines. ``col_map`` is the PartitionedPlanes global-column →
    packed-rule map (None = unpartitioned: bit position IS the rule
    index, bounded by ``n_rules``); partition padding (-1) never yields
    an index."""
    bits = np.unpackbits(
        np.ascontiguousarray(bits_row).view(np.uint8), bitorder="little"
    )
    if col_map is not None:
        mask = bits[: col_map.size].astype(bool)
        idx = col_map[np.nonzero(mask)[0]]
        return idx[(idx >= 0) & (idx < n_rules)]
    mask = bits[:n_rules].astype(bool)
    return np.nonzero(mask)[0]


def shard_partition(shard_id: str, n_partitions: int) -> int:
    """Stable (tier, bucket)-shard → mesh policy-partition assignment:
    identity-hashed like shard buckets themselves, so an edited shard
    stays on its owning device and dirties exactly one partition.
    blake2b for the same GF(2)-linearity reason as compiler/shard.py."""
    h = int.from_bytes(
        hashlib.blake2b(shard_id.encode(), digest_size=8).digest(), "big"
    )
    return h % max(1, n_partitions)


def _roundup(n: int, m: int) -> int:
    return -(-max(n, 1) // m) * m


class PartitionedPlanes:
    """Shard-aware placement of the packed policy tensors on a mesh.

    The legacy path (shard_codes_tensors) lets jax.device_put split the
    rule axis evenly — opaque slices, so ANY reload re-uploads every
    device's shard. This class instead lays the rule columns out BY
    compiler shard: each (tier, bucket) shard's rules land contiguously
    in the partition `shard_partition()` assigns, each partition pads to
    a common bucketed width, and the global arrays assemble from
    per-device pieces (jax.make_array_from_single_device_arrays). A
    reload reuses the prior placement's per-device buffers for every
    partition whose bytes are unchanged — an incremental one-shard edit
    re-uploads ONE partition's slice of W/thresh/group/policy and leaves
    every other device's HBM untouched (placement_transfer_count pins
    it).

    Column order is a permutation of the packed layout, which the
    first/last reductions never see (they reduce POLICY indices); the
    only rule-INDEX output is the diagnostics bitset, which decodes
    through ``col_map`` (global column → packed rule index, -1 padding).
    """

    def __init__(self, mesh: Mesh, n_partitions: int, r_part: int):
        self.mesh = mesh
        self.n_partitions = n_partitions
        self.r_part = r_part
        self.col_map: Optional[np.ndarray] = None
        self.shard_partition_map: Dict[str, int] = {}
        # (tensor name, partition) -> (digest, per-device single arrays)
        self._pieces: Dict[Tuple[str, int], Tuple[str, tuple]] = {}
        self.act_rows_dev = None
        self.W_dev = None
        self.thresh_dev = None
        self.rule_group_dev = None
        self.rule_policy_dev = None
        self.transfers_last_build = 0

    # ------------------------------------------------------------ building

    @staticmethod
    def plan(packed, policy_shard: Dict[str, str], n_partitions: int):
        """Per-partition packed-rule-index lists. Rules attribute through
        the pack's per-column back-map (rule_clause carries policy -1 for
        gate rules — those, and rules of unmapped policies, go to the
        residual partition 0)."""
        parts: List[List[int]] = [[] for _ in range(n_partitions)]
        sids: Dict[int, set] = {p: set() for p in range(n_partitions)}
        for r in range(packed.n_rules):
            rc = packed.rule_clause[r]
            sid = None
            if rc.pm_idx >= 0:
                sid = policy_shard.get(packed.policy_meta[rc.pm_idx].policy_id)
            p = shard_partition(sid, n_partitions) if sid is not None else 0
            parts[p].append(r)
            if sid is not None:
                sids[p].add(sid)
        return parts, sids

    @classmethod
    def build(
        cls,
        mesh: Mesh,
        packed,
        policy_shard: Dict[str, str],
        int8_plane: bool,
        prior: "Optional[PartitionedPlanes]" = None,
        max_rules_per_partition: Optional[int] = None,
        width_align: int = 64,
    ) -> "PartitionedPlanes":
        n_parts = mesh.shape["policy"]
        parts, sids = cls.plan(packed, policy_shard, n_parts)
        widest = max(len(p) for p in parts)
        # bucketed width: small edits that grow a shard keep the layout
        # (and therefore every clean partition's bytes) stable
        r_part = _roundup(widest, width_align)
        if (
            max_rules_per_partition is not None
            and r_part > max_rules_per_partition
        ):
            raise MeshCapacityError(
                f"partitioned plane needs {r_part} rule columns per device "
                f"(widest partition {widest}), over the "
                f"{max_rules_per_partition}-column device budget with "
                f"{n_parts} device partition(s) — add devices to the "
                "policy axis"
            )
        self = cls(mesh, n_parts, r_part)
        for p, ss in sids.items():
            for sid in ss:
                self.shard_partition_map[sid] = p
        if prior is not None and (
            prior.n_partitions != n_parts or prior.r_part != r_part
        ):
            prior = None  # layout changed: nothing is reusable

        L = packed.W.shape[0]
        w_dtype = np.int8 if int8_plane else jnp.bfloat16
        thresh_host = (
            packed.thresh.astype(np.int32) if int8_plane else packed.thresh
        )
        col_map = np.full(n_parts * r_part, -1, dtype=np.int32)
        w_parts, t_parts, g_parts, p_parts = [], [], [], []
        for p, rows in enumerate(parts):
            k = len(rows)
            col_map[p * r_part : p * r_part + k] = rows
            W_p = np.zeros((L, r_part), dtype=w_dtype)
            t_p = np.full((r_part,), 10**9, dtype=thresh_host.dtype)
            g_p = np.zeros((r_part,), dtype=packed.rule_group.dtype)
            pol_p = np.full(
                (r_part,), np.iinfo(np.int32).max, dtype=packed.rule_policy.dtype
            )
            if k:
                idx = np.asarray(rows, dtype=np.intp)
                W_p[:, :k] = np.asarray(packed.W, dtype=w_dtype)[:, idx]
                t_p[:k] = thresh_host[idx]
                g_p[:k] = packed.rule_group[idx]
                pol_p[:k] = packed.rule_policy[idx]
            w_parts.append(W_p)
            t_parts.append(t_p)
            g_parts.append(g_p)
            p_parts.append(pol_p)
        self.col_map = col_map

        R_total = n_parts * r_part
        self.W_dev = self._assemble(
            "W", w_parts, (L, R_total), P(None, "policy"), prior
        )
        self.thresh_dev = self._assemble(
            "thresh", t_parts, (R_total,), P("policy"), prior
        )
        self.rule_group_dev = self._assemble(
            "group", g_parts, (R_total,), P("policy"), prior
        )
        self.rule_policy_dev = self._assemble(
            "policy", p_parts, (R_total,), P("policy"), prior
        )
        self.act_rows_dev = self._assemble_replicated(
            "act_rows", packed.table.rows, prior
        )
        return self

    @staticmethod
    def _digest(block: np.ndarray) -> str:
        h = hashlib.blake2b(digest_size=16)
        h.update(str(block.shape).encode())
        h.update(np.dtype(block.dtype).str.encode())
        h.update(np.ascontiguousarray(block).tobytes())
        return h.hexdigest()

    def _put(self, block: np.ndarray, device):
        global _placement_transfers
        with _placement_lock:
            _placement_transfers += 1
        self.transfers_last_build += 1
        return jax.device_put(block, device)

    def _assemble(self, name, blocks, global_shape, spec, prior):
        """One global array from per-partition host blocks, reusing the
        prior placement's per-device pieces wherever the bytes match.

        Multi-process meshes (the pod): each process uploads ONLY the
        partitions that live on its own addressable devices and hands
        jax.make_array_from_single_device_arrays its local pieces — the
        multihost global-array idiom, no collective involved. A partition
        owned elsewhere still gets its digest recorded (empty piece
        tuple) so reuse bookkeeping stays uniform, but costs this host
        zero transfers — which is exactly the per-host pinning the pod
        dirty-swap tests gate on."""
        sharding = NamedSharding(self.mesh, spec)
        devs = np.asarray(self.mesh.devices)  # [data, policy]
        proc = jax.process_index()
        pieces: List = []
        for p, block in enumerate(blocks):
            digest = self._digest(block)
            local = [d for d in devs[:, p].flat if d.process_index == proc]
            held = prior._pieces.get((name, p)) if prior is not None else None
            if held is not None and held[0] == digest:
                per_dev = held[1]
            else:
                per_dev = tuple(self._put(block, dev) for dev in local)
            self._pieces[(name, p)] = (digest, per_dev)
            pieces.extend(per_dev)
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, pieces
        )

    def _assemble_replicated(self, name, block, prior):
        digest = self._digest(block)
        proc = jax.process_index()
        held = prior._pieces.get((name, 0)) if prior is not None else None
        if held is not None and held[0] == digest:
            per_dev = held[1]
        else:
            per_dev = tuple(
                self._put(block, dev)
                for dev in np.asarray(self.mesh.devices).flat
                if dev.process_index == proc
            )
        self._pieces[(name, 0)] = (digest, per_dev)
        return jax.make_array_from_single_device_arrays(
            block.shape, NamedSharding(self.mesh, P(*([None] * block.ndim))),
            list(per_dev),
        )


# --------------------------------------------------- production codes path


def shard_codes_tensors(mesh: Mesh, act_rows, W, thresh, rule_group, rule_policy):
    """Place the feature-code evaluation tensors: activation table
    replicated (every shard expands the same request features), rule axis
    sharded."""
    rep = NamedSharding(mesh, P(None, None))
    w_s = NamedSharding(mesh, P(None, "policy"))
    r_s = NamedSharding(mesh, P("policy"))
    return (
        jax.device_put(act_rows, rep),
        jax.device_put(W, w_s),
        jax.device_put(thresh, r_s),
        jax.device_put(rule_group, r_s),
        jax.device_put(rule_policy, r_s),
    )


# pjit step factory invocations: a fresh factory call is a fresh jit (and
# a first-call trace), so tests pin "an incremental swap builds no new
# mesh step" exactly like kernel_trace_count pins the XLA planes
_step_builds = 0


def mesh_step_build_count() -> int:
    return _step_builds


def sharded_codes_match_fn(
    mesh: Mesh,
    n_tiers: int,
    has_gate: bool = False,
    donate: bool = False,
    want_full: bool = True,
    replicated_out: bool = False,
):
    """The production evaluation step, sharded: feature codes in, packed
    uint32 verdict words out. This is the step TPUPolicyEngine.match_arrays
    routes through when the engine owns a mesh.

    - codes/extras shard over ``data`` (batch parallelism);
    - W [L, R] + rule tensors shard over ``policy`` (rule parallelism);
    - each shard computes its local per-(tier, effect) first/last-match
      extrema; the cross-shard combine is a min/max all-reduce XLA inserts
      from the sharding annotations — first-match is a min-reduction, so
      shard-and-reduce is exact;
    - the tier walk runs on the replicated [B, G] extrema, and the readback
      is 4 bytes per request, sharded over data.

    Returns (packed words [B], (first [B, G], last [B, G])) — the same
    surface as ops.match.match_rules_codes(want_full=True); has_gate adds
    the fallback-scope gate column and the WORD_GATE bit exactly like the
    single-device kernel.

    donate hands the per-batch codes/extras shards back to XLA as scratch
    (ops/match.py match_rules_codes_donated has the rationale); the
    engine enables it on TPU-class backends only — the CPU runtime may
    alias numpy inputs, which the engine's staging pool reuses.

    want_full=False is the SERVING variant: the per-shard partial
    verdicts still reduce on device, but only the one packed uint32 word
    per request leaves the computation — the [B, G] first/last extrema
    never materialize as outputs, so the per-request device→host payload
    is exactly 4 bytes however many devices the rules span.

    replicated_out=True (the pod regime — mesh_is_multiprocess) gathers
    every output to all devices: on a multi-host mesh a data-sharded
    output is only partially addressable per host, so the serving host
    could not read the rows that landed on its peers. The extra
    all-gather moves 4 bytes per request for the serving word."""
    global _step_builds
    _step_builds += 1
    G = n_tiers * 3 + (1 if has_gate else 0)
    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # codes [B, S]
        NamedSharding(mesh, P("data", None)),  # extras [B, E]
        NamedSharding(mesh, P(None, None)),  # act_rows [V, L]
        NamedSharding(mesh, P(None, "policy")),  # W [L, R]
        NamedSharding(mesh, P("policy")),  # thresh [R]
        NamedSharding(mesh, P("policy")),  # rule_group [R]
        NamedSharding(mesh, P("policy")),  # rule_policy [R]
    )
    out_b = P() if replicated_out else P("data")
    out_bg = P() if replicated_out else P("data", None)
    if want_full:
        out_shardings = (
            NamedSharding(mesh, out_b),  # packed words [B]
            NamedSharding(mesh, out_bg),  # first [B, G]
            NamedSharding(mesh, out_bg),  # last [B, G]
        )
    else:
        out_shardings = NamedSharding(mesh, out_b)  # packed words only

    @functools.partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(0, 1) if donate else (),
    )
    def step(codes, extras, act_rows, W, thresh, rule_group, rule_policy):
        lit = _lit_matrix_codes(
            codes, extras, act_rows, _lit_dtype(W.dtype)
        )  # [B, L]
        scores = _scores(lit, W)  # [B, R] — R sharded
        sat = scores >= thresh[None, :]
        masked_min = jnp.where(sat, rule_policy[None, :], INT32_MAX)
        masked_max = jnp.where(sat, rule_policy[None, :], -1)
        firsts = []
        lasts = []
        for g in range(G):
            in_g = (rule_group == g)[None, :]
            firsts.append(
                jnp.min(
                    jnp.where(in_g, masked_min, INT32_MAX),
                    axis=1,  # cross-shard min all-reduce over the policy axis
                )
            )
            lasts.append(
                # cross-shard max all-reduce; min != max flags multi-match
                jnp.max(jnp.where(in_g, masked_max, -1), axis=1)
            )
        first = jnp.stack(firsts, axis=1)  # [B, G] replicated on policy
        last = jnp.stack(lasts, axis=1)
        packed = _tier_walk(first, last, n_tiers)
        if has_gate:
            gate = (first[:, n_tiers * 3] != INT32_MAX).astype(jnp.uint32)
            packed = packed | (gate << 27)
        if not want_full:
            return packed
        return packed, first, last

    return step


def sharded_codes_bits_fn(mesh: Mesh, replicated_out: bool = False):
    """Sharded twin of ops.match.match_rules_codes_bits: per-rule
    satisfaction bitsets [B, R // 32] for diagnostic rendering. Each shard
    packs its contiguous rule range; the output sharding along the rule-word
    axis makes the host concatenation implicit (replicated_out gathers it
    everywhere instead — the pod regime, same rationale as the match step)."""
    global _step_builds
    _step_builds += 1
    from ..ops.match import _pack_sat_bits

    in_shardings = (
        NamedSharding(mesh, P("data", None)),  # codes
        NamedSharding(mesh, P("data", None)),  # extras
        NamedSharding(mesh, P(None, None)),  # act_rows
        NamedSharding(mesh, P(None, "policy")),  # W
        NamedSharding(mesh, P("policy")),  # thresh
    )
    out_shardings = NamedSharding(
        mesh, P() if replicated_out else P("data", "policy")
    )

    @functools.partial(
        jax.jit, in_shardings=in_shardings, out_shardings=out_shardings
    )
    def step(codes, extras, act_rows, W, thresh):
        lit = _lit_matrix_codes(codes, extras, act_rows, _lit_dtype(W.dtype))
        scores = _scores(lit, W)
        sat = scores >= thresh[None, :]
        return _pack_sat_bits(sat)

    return step
