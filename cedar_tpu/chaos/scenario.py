"""Game-day scenario files and the built-in scenario library.

A scenario is a JSON document (docs/resilience.md "Game days"):

    {
      "name": "kill-decode",
      "seed": 7,
      "description": "decode stage dies mid-traffic",
      "faults": [
        {"seam": "pipeline.decode_q", "kind": "kill", "after": 25, "count": 1}
      ],
      "slo": {"availability": 0.99, "recovery_p99_ratio": 3.0}
    }

``faults`` entries take the InjectionRule fields (after/count/probability/
rate/delay_s/message/replacement). ``slo`` thresholds are read by the
cedar-chaos runner and ``bench.py --chaos``; absent fields take the
DEFAULT_SLO values. Scheduling is fully deterministic (seeded PRNG, call
indices, token buckets) so a failing game day replays bit-for-bit.
"""

from __future__ import annotations

import json
from typing import Optional

DEFAULT_SLO = {
    # fraction of in-fault requests that must get a clean (no
    # evaluationError) answer
    "availability": 0.99,
    # recovered p99 may be at most this multiple of the pre-fault p99
    # (plus the absolute floor below — µs-scale p99s are all noise)
    "recovery_p99_ratio": 3.0,
    "recovery_p99_floor_ms": 20.0,
}


class ScenarioError(ValueError):
    """A scenario document failed validation."""


def load_scenario(doc) -> dict:
    """Validate a scenario dict (or parse a JSON string) into the shape
    configure()/the runners expect; raises ScenarioError on problems."""
    from .registry import SEAMS, _KINDS

    if isinstance(doc, (str, bytes)):
        try:
            doc = json.loads(doc)
        except ValueError as e:
            raise ScenarioError(f"scenario is not valid JSON: {e}") from None
    if not isinstance(doc, dict):
        raise ScenarioError("scenario must be a JSON object")
    faults = doc.get("faults")
    if not isinstance(faults, list) or not faults:
        raise ScenarioError('scenario needs a non-empty "faults" list')
    for i, f in enumerate(faults):
        if not isinstance(f, dict):
            raise ScenarioError(f"faults[{i}] must be an object")
        if f.get("seam") not in SEAMS:
            raise ScenarioError(
                f"faults[{i}]: unknown seam {f.get('seam')!r} "
                f"(known: {sorted(SEAMS)})"
            )
        if f.get("kind") not in _KINDS:
            raise ScenarioError(
                f"faults[{i}]: unknown kind {f.get('kind')!r} "
                f"(known: {_KINDS})"
            )
    out = dict(doc)
    out["slo"] = {**DEFAULT_SLO, **(doc.get("slo") or {})}
    return out


def load_scenario_file(path: str) -> dict:
    with open(path) as f:
        return load_scenario(f.read())


# ---------------------------------------------------------------- builtins

# the four canonical game days (ISSUE 6 / docs/resilience.md): each is a
# ready-to-run scenario the cedar-chaos CLI resolves by name and
# bench.py --chaos executes end to end against its in-process server.
BUILTIN_SCENARIOS = {
    "kill-decode": {
        "name": "kill-decode",
        "seed": 7,
        "description": "pipeline decode thread dies mid-traffic; the "
        "supervisor must revive the stage and shed its queued batches",
        "faults": [
            {"seam": "pipeline.decode_q", "kind": "kill", "after": 5,
             "count": 1, "message": "decode stage killed (game day)"},
        ],
    },
    "device-loss": {
        "name": "device-loss",
        "seed": 11,
        "description": "device dispatch starts failing fatally; the "
        "breaker must trip, the interpreter must carry traffic, and the "
        "device recovery must rebuild the engine and re-arm",
        "faults": [
            {"seam": "engine.dispatch", "kind": "error", "after": 3,
             "count": 8,
             "message": "UNAVAILABLE: device lost (game day)"},
        ],
    },
    "poison-crd": {
        "name": "poison-crd",
        "seed": 13,
        "description": "one CRD Policy object turns to garbage; it must "
        "be quarantined and serving must continue on the last-known-good "
        "content with /readyz still 200",
        "faults": [
            {"seam": "store.crd.object", "kind": "corrupt", "count": 3,
             "replacement": "permit (principal galaxy-brain;;; %%"},
        ],
    },
    "store-stall": {
        "name": "store-stall",
        "seed": 17,
        "description": "the policy store stalls on reload; serving must "
        "continue on the previous set with no availability dip",
        "faults": [
            {"seam": "store.load", "kind": "latency", "count": 2,
             "delay_s": 2.0},
        ],
        "slo": {"availability": 0.995},
    },
    "replica-loss": {
        "name": "replica-loss",
        "seed": 19,
        "description": "one fleet replica's batcher worker dies "
        "mid-traffic; the router must route around it with zero decision "
        "flips, and the supervisor must revive it (requires a fleet of "
        ">= 2 replicas — cedar-chaos --spawn starts one)",
        "faults": [
            {"seam": "fleet.replica_dispatch", "kind": "kill", "after": 10,
             "count": 1, "message": "replica killed (game day)"},
        ],
        "slo": {"availability": 0.995},
        # hints for cedar-chaos --spawn: the scenario needs a replicated
        # serving topology (ignored by /chaos/configure)
        "spawn_args": ["--fleet-replicas", "2"],
    },
    "shed-storm": {
        "name": "shed-storm",
        "seed": 23,
        "description": "the admission-control gate starts force-shedding "
        "a slice of admitted-looking traffic (the storm shape without "
        "needing real overload); every shed must answer honestly "
        "(NoOpinion + Retry-After / admission fail-mode), the device "
        "breaker must stay CLOSED throughout, and accounting must stay "
        "exact (offered == admitted + shed)",
        "faults": [
            {"seam": "load.shed", "kind": "corrupt", "after": 5,
             "probability": 0.5, "count": 200},
        ],
        "slo": {"availability": 0.0},  # sheds ARE the scenario: the gates
        # that matter are zero decision flips among served answers and a
        # closed breaker, asserted by the runner/tests directly
        "spawn_args": ["--max-inflight", "64"],
    },
    "lifecycle-breach": {
        "name": "lifecycle-breach",
        "seed": 29,
        "description": "a staged candidate goes bad mid-canary: canary-"
        "slice evaluations start erroring, the lifecycle controller's SLO "
        "burn gate must halt the rollout and roll the candidate back "
        "automatically, and live traffic must see zero decision flips "
        "(the canary slice answers from the live engine on candidate "
        "error, so availability holds)",
        "faults": [
            {"seam": "lifecycle.canary", "kind": "error", "after": 5,
             "probability": 0.8, "count": 200,
             "message": "candidate evaluation failed (game day)"},
        ],
        "slo": {"availability": 0.0},  # canary errors ARE the scenario:
        # the gates that matter — automatic rollback, zero decision flips
        # on live traffic — are asserted by bench --lifecycle / tests
    },
}


def builtin_scenario(name: str) -> Optional[dict]:
    doc = BUILTIN_SCENARIOS.get(name)
    return load_scenario(dict(doc)) if doc is not None else None
