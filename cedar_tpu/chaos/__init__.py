"""cedar_tpu.chaos — fault injection + game-day scenarios.

The registry (registry.py) holds the named seams threaded through every
serving layer; scenario.py holds the scenario file format and the built-in
game days; the cedar-chaos CLI (cli/chaos.py) drives scenarios against a
live server. docs/resilience.md "Game days" is the runbook.
"""

from .registry import (
    SEAMS,
    ChaosError,
    ChaosRegistry,
    InjectionRule,
    Seam,
    ThreadKilled,
    TokenBucket,
    chaos_fire,
    default_registry,
)
from .scenario import (
    BUILTIN_SCENARIOS,
    DEFAULT_SLO,
    ScenarioError,
    builtin_scenario,
    load_scenario,
    load_scenario_file,
)

__all__ = [
    "SEAMS",
    "ChaosError",
    "ChaosRegistry",
    "InjectionRule",
    "Seam",
    "ThreadKilled",
    "TokenBucket",
    "chaos_fire",
    "default_registry",
    "BUILTIN_SCENARIOS",
    "DEFAULT_SLO",
    "ScenarioError",
    "builtin_scenario",
    "load_scenario",
    "load_scenario_file",
]
