"""Fault-injection seam registry: one config surface for every internal
failure mode the serving stack can suffer.

Every layer of the serving path exposes *named seams* — store load/reload,
cache get/put, native encode, device dispatch/decode, the pipeline's
hand-off queues, the shadow offer/process hooks, the rollout lifecycle,
and the reference-parity ``response`` injector — as `chaos_fire(seam)`
calls. With the registry disarmed (the production state) a fire is one
module attribute read and a returned payload: no locks, no clock reads,
no allocation, and live responses are byte-identical to a build without
the plane (tests/test_resilience.py pins the differential). Armed, each
configured seam applies its scenario rules in order:

  * ``error``    — raise ChaosError (a wedged/raising dependency)
  * ``latency``  — sleep ``delay_s`` (a stalled store / slow device)
  * ``corrupt``  — transform the payload (a poison policy object)
  * ``kill``     — raise ThreadKilled, a BaseException that sails past
                   the per-batch ``except Exception`` containment and
                   unwinds the worker thread (a stage death)
  * ``response_error`` / ``response_deny`` — the reference
    error-injector's artificial NoOpinion/Deny swaps on the ``response``
    seam's (decision, reason, error) payload

Rule scheduling is deterministic: ``after``/``count`` schedule by the
seam's call index, ``probability`` draws from the scenario's seeded PRNG,
and ``rate`` uses the reference's burst-1 token bucket — no wall-clock
randomness anywhere, so a scenario replays identically (docs/resilience.md
has the scenario file format and the seam catalogue).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


class ChaosError(RuntimeError):
    """An injected dependency failure (the successor of the
    error_injector's InjectedFault for seam-scripted faults)."""


class ThreadKilled(BaseException):
    """An injected thread death. Deliberately NOT an Exception: the worker
    loops contain per-batch ``except Exception`` (and the batcher's
    per-batch ``except BaseException`` guards sit *inside* the loop, after
    the seam fire points), so this unwinds the whole thread exactly like a
    C-extension crash or interpreter teardown would."""


class TokenBucket:
    """Token bucket: ``rate`` tokens/second, burst 1 (golang.org/x/time/rate
    semantics as used by the reference error injector with burst=1). The
    one rate-limiter shared by the ``response`` seam, the BatchFaultInjector
    test machinery, and rate-scheduled scenario rules."""

    def __init__(self, rate: float, now=time.monotonic):
        self.rate = rate
        self._now = now
        self._tokens = 1.0 if rate > 0 else 0.0
        self._last = now()
        self._lock = threading.Lock()

    def allow(self) -> bool:
        if self.rate <= 0:
            return False
        with self._lock:
            now = self._now()
            self._tokens = min(1.0, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


# seam catalogue: name -> where it fires (docs/resilience.md renders this
# table; cedar-chaos --list-seams prints it). Instrumentation sites fire
# seams not listed here at their peril — configure() rejects unknown names
# so a typo'd scenario fails loudly instead of silently injecting nothing.
SEAMS = {
    "store.load": "directory store load_policies / reloader tier fetch",
    "store.crd.relist": "CRD store list + watch-reconnect relist",
    "store.crd.object": "per-CRD-object policy text parse (corruptible)",
    "cache.get": "decision cache lookup",
    "cache.put": "decision cache insert",
    "engine.encode": "native/host batch encode (fastpath._encode_chunk)",
    "engine.dispatch": "device batch launch (fastpath + evaluator paths)",
    "engine.shard_compile": "per-dirty-shard lowering inside the "
    "incremental compiler (compiler/shard.py ShardCompiler.compile)",
    "engine.decode": "device readback + verdict decode",
    "pipeline.collect": "batcher worker loop after claiming a batch",
    "pipeline.dispatch_q": "pipeline dispatch stage after queue get",
    "pipeline.decode_q": "pipeline decode stage after queue get",
    "shadow.offer": "shadow-evaluation offer hook (live request side)",
    "shadow.process": "shadow worker batch processing",
    "rollout.stage": "rollout candidate staging",
    "rollout.promote": "rollout promotion",
    "fleet.route": "fleet router replica selection (request thread)",
    "fleet.hedge": "hedged-dispatch fire point (lone-request tail hedge)",
    "fleet.replica_dispatch": "replica batcher worker loop after claiming "
    "a batch (kill = one replica lost)",
    "fleet.promote": "per-replica compiled-set swap inside the fleet "
    "promotion barrier",
    "fanout.route": "front-end worker selection (cedar_tpu/fanout): fired "
    "with the chosen worker id before the request is handed over",
    "fanout.worker_kill": "inside a fanout worker's request handling "
    "(kill = that worker process lost; the front-end rehashes around it)",
    "fanout.swap": "per-worker compiled-set swap inside the cross-process "
    "generation barrier (frontend.load / promote)",
    "cache.peer_fetch": "peer decision-cache traffic (fetch AND gossip "
    "delivery) between fanout workers",
    "load.shed": "admission-control gate verdict (cedar_tpu/load): a "
    "`corrupt` rule forces the verdict to a shed — storm game days prove "
    "the shed answer path and the breaker's indifference to it",
    "lifecycle.gate": "lifecycle gate evaluation (cedar_tpu/lifecycle): "
    "fired before each verify/shadow/canary evidence check — `error` "
    "rules exercise the transient-retry path, `kill` a controller crash "
    "at a stage boundary",
    "lifecycle.canary": "per-request canary-slice candidate evaluation "
    "inside the lifecycle canary router — an `error` rule makes the "
    "canary slice burn its SLO budget (the lifecycle-breach game day)",
    "lifecycle.journal": "lifecycle journal append (crash-point seam: "
    "`kill` = controller dies mid-transition; resume must replay)",
    "response": "final (decision, reason, error) swap (reference parity)",
}

RESPONSE_SEAM = "response"

_KINDS = (
    "error", "latency", "corrupt", "kill", "response_error", "response_deny",
)


class InjectionRule:
    """One scheduled fault on one seam (see module docstring for kinds).

    Scheduling fields (all optional, ANDed):
      after        skip the first N eligible calls of the seam
      count        fire at most N times (None = unlimited)
      probability  fire with this chance per call (seeded PRNG)
      rate         token-bucket fires/second (reference limiter semantics)
    """

    def __init__(
        self,
        kind: str,
        after: int = 0,
        count: Optional[int] = None,
        probability: Optional[float] = None,
        rate: Optional[float] = None,
        delay_s: float = 0.0,
        message: str = "",
        replacement: Optional[str] = None,
        now=time.monotonic,
    ):
        if kind not in _KINDS:
            raise ValueError(f"unknown chaos rule kind {kind!r}")
        self.kind = kind
        self.after = max(0, int(after))
        self.count = None if count is None else max(0, int(count))
        self.probability = probability
        self.delay_s = float(delay_s)
        self.message = message or f"injected {kind}"
        self.replacement = replacement
        self.fired = 0
        self._limiter = None if rate is None else TokenBucket(rate, now)

    def should_fire(self, call_index: int, rng) -> bool:
        if call_index < self.after:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        if self.probability is not None and rng.random() >= self.probability:
            return False
        if self._limiter is not None and not self._limiter.allow():
            return False
        self.fired += 1
        return True

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "after": self.after,
            "count": self.count,
            "probability": self.probability,
            "delay_s": self.delay_s,
            "fired": self.fired,
        }


def _default_corrupt(payload, rule: InjectionRule):
    """Generic payload corruption when the fire site supplies no
    corrupter: strings/bytes are replaced with (or poisoned by) the rule's
    replacement text — enough to turn a policy document into a parse
    failure, which is what poison-object scenarios want."""
    poison = rule.replacement if rule.replacement is not None else (
        "%% chaos-injected corruption %%"
    )
    if isinstance(payload, str):
        return poison
    if isinstance(payload, (bytes, bytearray)):
        return poison.encode()
    return payload


class Seam:
    """One named injection point and its configured rules. A Seam may be
    owned by the shared registry (scenario-driven) or held privately (the
    ErrorInjector's reference-parity ``response`` seam)."""

    def __init__(self, name: str, sleep=time.sleep):
        self.name = name
        self.rules: list = []
        self.calls = 0
        self._sleep = sleep
        self._lock = threading.Lock()

    def add_rule(self, rule: InjectionRule) -> None:
        self.rules.append(rule)

    def fire(self, payload=None, corrupter=None, rng=None, on_fire=None):
        """Apply this seam's rules to one call; returns the (possibly
        transformed) payload or raises the injected failure."""
        with self._lock:
            idx = self.calls
            self.calls += 1
        for rule in self.rules:
            with self._lock:
                hit = rule.should_fire(idx, rng)
            if not hit:
                continue
            if on_fire is not None:
                on_fire(self.name, rule.kind)
            if rule.kind == "latency":
                self._sleep(rule.delay_s)
            elif rule.kind == "corrupt":
                if corrupter is not None:
                    payload = corrupter(payload)
                else:
                    payload = _default_corrupt(payload, rule)
            elif rule.kind == "kill":
                raise ThreadKilled(f"{self.name}: {rule.message}")
            elif rule.kind == "error":
                raise ChaosError(f"{self.name}: {rule.message}")
            elif rule.kind == "response_error":
                payload = ("no_opinion", "", "encountered error")
            elif rule.kind == "response_deny":
                payload = ("deny", "Authorization denied", None)
        return payload

    def stats(self) -> dict:
        return {
            "calls": self.calls,
            "rules": [r.to_dict() for r in self.rules],
        }


class ChaosRegistry:
    """The scenario-driven seam registry. One module-level default instance
    backs the `chaos_fire` helper the instrumentation sites call; tests and
    the cedar-chaos runner configure/arm/disarm it.

    `armed` is read lock-free on the hot path: arming takes effect at the
    next fire, which is all a game-day needs."""

    def __init__(self):
        self._seams: dict = {}
        self._lock = threading.Lock()
        self.armed = False
        self.scenario_name = ""
        self._rng = __import__("random").Random(0)

    # ------------------------------------------------------------- lifecycle

    def configure(self, scenario: dict) -> None:
        """Install a scenario: {"name": ..., "seed": int, "faults":
        [{"seam": ..., "kind": ..., ...rule fields}]}. Replaces any prior
        configuration; does NOT arm. Unknown seam names or rule kinds are
        rejected outright — a typo must not silently inject nothing."""
        import random

        faults = scenario.get("faults") or []
        seams: dict = {}
        for f in faults:
            name = f.get("seam", "")
            if name not in SEAMS:
                raise ValueError(
                    f"unknown chaos seam {name!r}; known: {sorted(SEAMS)}"
                )
            rule = InjectionRule(
                kind=f.get("kind", ""),
                after=f.get("after", 0),
                count=f.get("count"),
                probability=f.get("probability"),
                rate=f.get("rate"),
                delay_s=f.get("delay_s", 0.0),
                message=f.get("message", ""),
                replacement=f.get("replacement"),
            )
            seams.setdefault(name, Seam(name)).add_rule(rule)
        with self._lock:
            self._seams = seams
            self.scenario_name = scenario.get("name", "")
            self._rng = random.Random(int(scenario.get("seed", 0)))

    def arm(self) -> None:
        self.armed = True

    def disarm(self) -> None:
        self.armed = False

    def reset(self) -> None:
        """Disarm and drop the configured scenario + all counters."""
        with self._lock:
            self.armed = False
            self._seams = {}
            self.scenario_name = ""

    # --------------------------------------------------------------- firing

    def fire(self, name: str, payload=None, corrupter=None):
        """Hot-path entry: with no armed scenario (or no rules on this
        seam) the payload passes straight through."""
        if not self.armed:
            return payload
        seam = self._seams.get(name)
        if seam is None:
            return payload
        return seam.fire(
            payload, corrupter=corrupter, rng=self._rng,
            on_fire=_record_injection,
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "armed": self.armed,
                "scenario": self.scenario_name,
                "seams": {n: s.stats() for n, s in self._seams.items()},
            }


def _record_injection(seam: str, kind: str) -> None:
    try:
        from ..server.metrics import record_chaos_injection

        record_chaos_injection(seam, kind)
    except Exception:  # noqa: BLE001 — metrics must never break injection
        log.debug("chaos injection metric publish failed", exc_info=True)


_default = ChaosRegistry()


def default_registry() -> ChaosRegistry:
    return _default


def chaos_fire(name: str, payload=None, corrupter=None):
    """The instrumentation-site helper. Disarmed (the production state)
    this is one attribute read and a return — behavior and bytes identical
    to not having the plane at all."""
    if not _default.armed:
        return payload
    return _default.fire(name, payload, corrupter=corrupter)
