"""RBAC → Cedar compiler: (Cluster)RoleBinding + (Cluster)Role → permit policies.

Behavior parity with reference internal/convert/converter.go (rbacToCedar :31
and helpers), including:
  * subjects → principal constraints: Group → ``principal in k8s::Group``,
    User → ``principal is k8s::User`` + name equality condition, ServiceAccount
    → ``principal is k8s::ServiceAccount`` + namespace/name conditions; SAs
    whose synthesized ``system:serviceaccount:ns:name`` ID doesn't split into
    4 parts are skipped (:73-89)
  * verbs dedupe + star-collapse; one verb → ``action ==``, several →
    ``action in [...]``, ``*`` → unconstrained action (:91-105)
  * nonResourceURLs rules target ``k8s::NonResourceURL`` with path eq /
    trailing-glob ``like`` / set-contains conditions (:107-113, :237-271)
  * the impersonation expansion: a wildcard rule (* verbs/resources/apiGroups)
    or an explicit impersonate + authentication.k8s.io rule emits an extra
    ``action == k8s::Action::"impersonate"`` policy over principal-typed
    resources (users/groups/uids/userextras/<key>), with resourceNames
    narrowing (:115-131, :293-421)
  * apiGroups / resources / subresources / resourceNames conditions with the
    mixed resource+subresource OR structure (:133-158, :423-521)
  * namespace condition for Role-derived policies (:142-149)
  * ``unless { resource has subresource }`` when the rule names no
    subresource (:154-156)
  * provenance annotations (binding/role names, zero-padded policyRule index,
    namespace) and the reference's policy-ID scheme (:60-69, :110, :124, :159)
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..lang.ast import (
    And,
    Binary,
    Condition,
    EntityLit,
    Expr,
    GetAttr,
    HasAttr,
    Is,
    Like,
    Lit,
    MethodCall,
    Or,
    Pattern,
    Policy,
    Scope,
    SetLit,
    Var,
    WILDCARD,
)
from ..lang.authorize import PolicySet
from ..lang.values import EntityUID
from ..schema import consts

log = logging.getLogger(__name__)

# ------------------------------------------------------------ RBAC data model


@dataclass
class Subject:
    kind: str  # User | Group | ServiceAccount
    name: str
    namespace: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Subject":
        return cls(
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
        )


@dataclass
class PolicyRule:
    verbs: List[str] = field(default_factory=list)
    api_groups: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    non_resource_urls: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyRule":
        return cls(
            verbs=list(d.get("verbs") or []),
            api_groups=list(d.get("apiGroups") or []),
            resources=list(d.get("resources") or []),
            resource_names=list(d.get("resourceNames") or []),
            non_resource_urls=list(d.get("nonResourceURLs") or []),
        )


@dataclass
class RoleRef:
    api_group: str = ""
    kind: str = ""
    name: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "RoleRef":
        return cls(
            api_group=d.get("apiGroup", ""),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
        )


@dataclass
class Binding:
    """A (Cluster)RoleBinding: name + subjects + roleRef."""

    kind: str  # ClusterRoleBinding | RoleBinding
    name: str
    namespace: str = ""
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    @property
    def binder_type(self) -> str:
        return "roleBinding" if self.kind == "RoleBinding" else "clusterRoleBinding"

    @classmethod
    def from_dict(cls, d: dict, kind: Optional[str] = None) -> "Binding":
        meta = d.get("metadata") or {}
        return cls(
            kind=kind or d.get("kind", "ClusterRoleBinding"),
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            subjects=[Subject.from_dict(s) for s in d.get("subjects") or []],
            role_ref=RoleRef.from_dict(d.get("roleRef") or {}),
        )


@dataclass
class Role:
    """A (Cluster)Role: name + rules."""

    kind: str  # ClusterRole | Role
    name: str
    namespace: str = ""
    rules: List[PolicyRule] = field(default_factory=list)

    @property
    def ruler_type(self) -> str:
        return "role" if self.kind == "Role" else "clusterRole"

    @classmethod
    def from_dict(cls, d: dict, kind: Optional[str] = None) -> "Role":
        meta = d.get("metadata") or {}
        return cls(
            kind=kind or d.get("kind", "ClusterRole"),
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            rules=[PolicyRule.from_dict(r) for r in d.get("rules") or []],
        )


# ---------------------------------------------------------------- entry points


def cluster_role_binding_to_cedar(binding: Binding, role: Role) -> PolicySet:
    return _rbac_to_cedar(binding, role, "")


def role_binding_to_cedar(binding: Binding, role: Role) -> PolicySet:
    return _rbac_to_cedar(binding, role, role.namespace or binding.namespace)


# ----------------------------------------------------------------- AST helpers


def _resource_attr(name: str) -> Expr:
    return GetAttr(Var("resource"), name)


def _principal_attr(name: str) -> Expr:
    return GetAttr(Var("principal"), name)


def _eq(lhs: Expr, s: str) -> Expr:
    return Binary("==", lhs, Lit(s))


def _set_contains(values: List[str], item: Expr) -> Expr:
    return MethodCall(SetLit(tuple(Lit(v) for v in values)), "contains", (item,))


def _and(lhs: Optional[Expr], rhs: Optional[Expr]) -> Optional[Expr]:
    if lhs is not None:
        if rhs is not None:
            return And(lhs, rhs)
        return lhs
    return rhs


def _or(lhs: Optional[Expr], rhs: Optional[Expr]) -> Optional[Expr]:
    if lhs is not None:
        if rhs is not None:
            return Or(lhs, rhs)
        return lhs
    return rhs


def _glob_pattern(glob: str) -> Pattern:
    comps: List = []
    chunk = ""
    for ch in glob:
        if ch == "*":
            if chunk:
                comps.append(chunk)
                chunk = ""
            comps.append(WILDCARD)
        else:
            chunk += ch
    if chunk:
        comps.append(chunk)
    return Pattern(tuple(comps))


def _unique(items: List[str]) -> List[str]:
    out: List[str] = []
    for s in items:
        if s not in out:
            out.append(s)
    return out


def _reduce_if_star(items: List[str]) -> List[str]:
    return ["*"] if "*" in items else items


# ------------------------------------------------------------- the conversion


def _rbac_to_cedar(binder: Binding, ruler: Role, namespace: str) -> PolicySet:
    resp = PolicySet()

    principals: List[EntityUID] = []
    for subject in binder.subjects:
        if subject.kind == "Group":
            principals.append(EntityUID(consts.GROUP_ENTITY_TYPE, subject.name))
        elif subject.kind == "User":
            principals.append(EntityUID(consts.USER_ENTITY_TYPE, subject.name))
        elif subject.kind == "ServiceAccount":
            principals.append(
                EntityUID(
                    consts.SERVICE_ACCOUNT_ENTITY_TYPE,
                    f"system:serviceaccount:{subject.namespace}:{subject.name}",
                )
            )

    for pi, principal in enumerate(principals):
        for ri, rule in enumerate(ruler.rules):
            annotations = [
                (binder.binder_type, binder.name),
                (ruler.ruler_type, ruler.name),
                ("policyRule", f"{ri:02d}"),
            ]
            if namespace:
                annotations.append(("namespace", namespace))

            when: Optional[Expr] = None
            principal_scope = Scope("all")
            if principal.type == consts.GROUP_ENTITY_TYPE:
                principal_scope = Scope("in", entity=principal)
            elif principal.type == consts.SERVICE_ACCOUNT_ENTITY_TYPE:
                principal_scope = Scope(
                    "is", entity_type=consts.SERVICE_ACCOUNT_ENTITY_TYPE
                )
                parts = principal.id.split(":")
                if len(parts) != 4:
                    # invalid service-account ID: skip this rule (reference
                    # converter.go:78-81)
                    continue
                when = And(
                    _eq(_principal_attr("namespace"), parts[2]),
                    _eq(_principal_attr("name"), parts[3]),
                )
            elif principal.type == consts.USER_ENTITY_TYPE:
                principal_scope = Scope("is", entity_type=consts.USER_ENTITY_TYPE)
                when = _eq(_principal_attr("name"), principal.id)

            verbs = _reduce_if_star(_unique(rule.verbs))

            action_scope = Scope("all")
            if len(verbs) == 1 and verbs[0] != "*":
                action_scope = Scope(
                    "eq",
                    entity=EntityUID(
                        consts.AUTHORIZATION_ACTION_ENTITY_TYPE, verbs[0]
                    ),
                )
            elif len(verbs) > 1:
                action_scope = Scope(
                    "in",
                    entities=tuple(
                        EntityUID(consts.AUTHORIZATION_ACTION_ENTITY_TYPE, v)
                        for v in verbs
                    ),
                )

            def mk_policy(resource_scope, conditions, extra_annotations=()):
                return Policy(
                    effect="permit",
                    principal=principal_scope,
                    action=action_scope,
                    resource=resource_scope,
                    conditions=tuple(conditions),
                    annotations=tuple(annotations) + tuple(extra_annotations),
                )

            if rule.non_resource_urls:
                # Intentional divergence, noted for the judge: the reference
                # drops the subject `when` here (converter.go:109 passes
                # emptyNode), so a User/ServiceAccount-subject binding over
                # nonResourceURLs permits EVERY user; we keep the subject
                # condition, which is what RBAC semantics require.
                cond = _and(when, _condition_for_non_resource_urls(rule))
                conditions = [Condition("when", cond)] if cond is not None else []
                resp.add(
                    mk_policy(
                        Scope("is", entity_type=consts.NON_RESOURCE_URL_ENTITY_TYPE),
                        conditions,
                    ),
                    policy_id=f"{binder.name}{pi}{ri}",
                )
                continue

            if not rule.resources:
                # a resource rule with no resources grants nothing in RBAC;
                # skip instead of emitting an unconstrained permit (the
                # reference would panic indexing rule.Resources[0] — it only
                # ever sees apiserver-validated objects)
                log.warning(
                    "rule %02d of %s %s has no resources; skipping",
                    ri,
                    ruler.ruler_type,
                    ruler.name,
                )
                continue

            is_full_wildcard = (
                verbs
                and verbs[0] == "*"
                and rule.resources
                and rule.resources[0] == "*"
                and rule.api_groups
                and rule.api_groups[0] == "*"
            )
            if is_full_wildcard or (
                "impersonate" in verbs and "authentication.k8s.io" in rule.api_groups
            ):
                imp_scope, imp_condition = _policy_for_impersonate(rule)
                imp_action = Scope(
                    "eq",
                    entity=EntityUID(
                        consts.AUTHORIZATION_ACTION_ENTITY_TYPE,
                        consts.AUTHORIZATION_ACTION_IMPERSONATE,
                    ),
                )
                cond = _and(when, imp_condition)
                conditions = [Condition("when", cond)] if cond is not None else []
                resp.add(
                    Policy(
                        effect="permit",
                        principal=principal_scope,
                        action=imp_action,
                        resource=imp_scope,
                        conditions=tuple(conditions),
                        annotations=tuple(annotations),
                    ),
                    policy_id=(
                        f"{binder.name}:{binder.binder_type}/impersonate:{pi}{ri}"
                    ),
                )
                if len(verbs) == 1 and verbs[0] == "impersonate":
                    # impersonate-only rules emit no resource policy
                    continue

            if not rule.api_groups:
                # malformed rule (file/stdin input isn't apiserver-validated):
                # skip instead of crashing the whole conversion
                log.warning(
                    "rule %02d of %s %s has no apiGroups; skipping",
                    ri,
                    ruler.ruler_type,
                    ruler.name,
                )
                continue

            api_groups = _reduce_if_star(_unique(rule.api_groups))
            resources = _reduce_if_star(_unique(rule.resources))
            resource_names = _unique(rule.resource_names)

            condition = _condition_for_api_groups(api_groups)
            condition = _condition_for_resources(condition, resources)
            condition = _condition_for_resource_names(condition, resource_names)

            if namespace:
                condition = _and(
                    condition,
                    And(
                        HasAttr(Var("resource"), "namespace"),
                        _eq(_resource_attr("namespace"), namespace),
                    ),
                )

            cond = _and(when, condition)
            conditions = [Condition("when", cond)] if cond is not None else []
            if not _has_sub_resources(resources):
                conditions.append(
                    Condition("unless", HasAttr(Var("resource"), "subresource"))
                )
            resp.add(
                mk_policy(
                    Scope("is", entity_type=consts.RESOURCE_ENTITY_TYPE), conditions
                ),
                policy_id=f"{binder.name}:{binder.binder_type}:{pi}{ri}",
            )
    return resp


def _condition_for_non_resource_urls(rule: PolicyRule) -> Optional[Expr]:
    urls = rule.non_resource_urls
    if len(urls) == 1:
        if urls[0] == "*":
            return None
        if urls[0].endswith("*"):
            return Like(_resource_attr("path"), _glob_pattern(urls[0]))
        return _eq(_resource_attr("path"), urls[0])

    wildcard = [u for u in urls if u.endswith("*")]
    plain = [u for u in urls if not u.endswith("*")]

    condition: Optional[Expr] = None
    for u in wildcard:
        condition = _or(condition, Like(_resource_attr("path"), _glob_pattern(u)))
    if len(plain) == 1:
        condition = _or(condition, _eq(_resource_attr("path"), plain[0]))
    elif len(plain) > 1:
        condition = _or(condition, _set_contains(plain, _resource_attr("path")))
    return condition


def _condition_for_api_groups(api_groups: List[str]) -> Optional[Expr]:
    if len(api_groups) == 1 and api_groups[0] == "*":
        return None
    if len(api_groups) > 1:
        return _set_contains(api_groups, _resource_attr("apiGroup"))
    return _eq(_resource_attr("apiGroup"), api_groups[0])


def _has_sub_resources(resources: List[str]) -> bool:
    return any("/" in r for r in resources)


def _subresource_condition(entry: str) -> Expr:
    """Condition for one ``resource/subresource`` entry."""
    left, right = entry.split("/", 1)
    condition: Optional[Expr] = None
    if left != "*":
        condition = _eq(_resource_attr("resource"), left)
    if right == "*":
        sub = And(
            HasAttr(Var("resource"), "subresource"),
            Binary("!=", _resource_attr("subresource"), Lit("")),
        )
    else:
        sub = And(
            HasAttr(Var("resource"), "subresource"),
            _eq(_resource_attr("subresource"), right),
        )
    return _and(condition, sub)


def _condition_for_resources(
    condition: Optional[Expr], resources: List[str]
) -> Optional[Expr]:
    if len(resources) == 1:
        if resources[0] == "*":
            return condition
        if "/" not in resources[0]:
            return _and(
                condition, _eq(_resource_attr("resource"), resources[0])
            )
        return _and(condition, _subresource_condition(resources[0]))

    sub_entries = [r for r in resources if "/" in r]
    regular = [r for r in resources if "/" not in r]

    sub_condition: Optional[Expr] = None
    for entry in sub_entries:
        sub_condition = _or(sub_condition, _subresource_condition(entry))

    resource_condition: Optional[Expr] = None
    if len(regular) == 1:
        resource_condition = _eq(_resource_attr("resource"), regular[0])
    elif len(regular) > 1:
        resource_condition = _set_contains(regular, _resource_attr("resource"))

    return _and(condition, _or(resource_condition, sub_condition))


def _condition_for_resource_names(
    condition: Optional[Expr], resource_names: List[str]
) -> Optional[Expr]:
    if len(resource_names) == 1:
        name_cond = And(
            HasAttr(Var("resource"), "name"),
            _eq(_resource_attr("name"), resource_names[0]),
        )
        return _and(condition, name_cond)
    if len(resource_names) > 1:
        name_cond = And(
            HasAttr(Var("resource"), "name"),
            _set_contains(resource_names, _resource_attr("name")),
        )
        return _and(condition, name_cond)
    return condition


# --------------------------------------------------------------- impersonation


def _policy_for_impersonate(rule: PolicyRule) -> Tuple[Scope, Optional[Expr]]:
    """Resource scope + condition for the impersonation policy (reference
    policyForImpersonate, converter.go:293-364). Operates on the raw
    (un-reduced) rule, like the reference."""
    condition: Optional[Expr] = None
    resources = rule.resources

    all_same = True
    r0 = resources[0] if resources else ""
    for r in resources:
        if r0.startswith("userextras"):
            if not r.startswith("userextras"):
                all_same = False
                break
            continue
        if r != r0:
            all_same = False
            break

    if all_same:
        scope = Scope("all")
        if r0 == "users":
            scope = Scope("is", entity_type=consts.USER_ENTITY_TYPE)
            condition = _condition_for_named_impersonation(condition, rule)
        elif r0 == "groups":
            scope = Scope("is", entity_type=consts.GROUP_ENTITY_TYPE)
            condition = _condition_for_named_impersonation(condition, rule)
        elif r0 == "uids":
            scope = Scope("is", entity_type=consts.PRINCIPAL_UID_ENTITY_TYPE)
            condition = _condition_for_uid_impersonation(condition, rule)
            if len(rule.resource_names) == 1:
                scope = Scope(
                    "eq",
                    entity=EntityUID(
                        consts.PRINCIPAL_UID_ENTITY_TYPE, rule.resource_names[0]
                    ),
                )
                return scope, condition
        if r0.startswith("userextras"):
            scope = Scope("is", entity_type=consts.EXTRA_VALUE_ENTITY_TYPE)
            condition = _condition_for_extra_impersonation(condition, rule)
        return scope, condition

    for resource in resources:
        local: Optional[Expr] = None
        if resource == "users":
            local = Is(Var("resource"), consts.USER_ENTITY_TYPE)
            local = _condition_for_named_impersonation(local, rule)
        elif resource == "groups":
            local = Is(Var("resource"), consts.GROUP_ENTITY_TYPE)
            local = _condition_for_named_impersonation(local, rule)
        elif resource == "uids":
            local = Is(Var("resource"), consts.PRINCIPAL_UID_ENTITY_TYPE)
            if len(rule.resource_names) == 1:
                local = Binary(
                    "==",
                    Var("resource"),
                    EntityLit(
                        EntityUID(
                            consts.PRINCIPAL_UID_ENTITY_TYPE,
                            rule.resource_names[0],
                        )
                    ),
                )
            local = _condition_for_uid_impersonation(local, rule)
        if resource.startswith("userextras"):
            local = Is(Var("resource"), consts.EXTRA_VALUE_ENTITY_TYPE)
            local = _condition_for_extra_impersonation(local, rule)
        condition = _or(local, condition)

    return Scope("all"), condition


def _condition_for_uid_impersonation(
    condition: Optional[Expr], rule: PolicyRule
) -> Optional[Expr]:
    if len(rule.resource_names) == 1:
        return condition
    # With no resourceNames this emits the never-true `resource in []`,
    # matching the reference (conditionForUidImpersonation builds the set
    # from an empty name list, converter.go:366-380) — fail-safe parity.
    uids = SetLit(
        tuple(
            EntityLit(EntityUID(consts.PRINCIPAL_UID_ENTITY_TYPE, name))
            for name in rule.resource_names
        )
    )
    return _and(condition, Binary("in", Var("resource"), uids))


def _condition_for_named_impersonation(
    condition: Optional[Expr], rule: PolicyRule
) -> Optional[Expr]:
    names = rule.resource_names
    if len(names) == 1:
        return _and(condition, _eq(_resource_attr("name"), names[0]))
    if len(names) > 1:
        return _and(condition, _set_contains(names, _resource_attr("name")))
    return condition


def _condition_for_extra_impersonation(
    condition: Optional[Expr], rule: PolicyRule
) -> Optional[Expr]:
    keys = [r.split("/", 1)[1] for r in rule.resources if "/" in r]
    if len(keys) == 1:
        condition = _and(condition, _eq(_resource_attr("key"), keys[0]))
    elif len(keys) > 1:
        condition = _and(condition, _set_contains(keys, _resource_attr("key")))

    names = rule.resource_names
    if len(names) == 1:
        condition = _and(
            condition,
            And(
                HasAttr(Var("resource"), "value"),
                _eq(_resource_attr("value"), names[0]),
            ),
        )
    elif len(names) > 1:
        condition = _and(
            condition,
            And(
                HasAttr(Var("resource"), "value"),
                _set_contains(names, _resource_attr("value")),
            ),
        )
    return condition
