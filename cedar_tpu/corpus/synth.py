"""Seeded synthesis of realistic org-wide (multi-cluster) policy sets.

The scale story (ROADMAP open item 4 / docs/performance.md "Giant policy
sets") needs corpora with two properties real 100k-rule org stores have
and the 10k bench generator lacks:

  * **cluster locality** — most policies target ONE cluster's API groups
    (the serving-partition discriminator rides ``resource.apiGroup`` as
    the first ``when`` conjunct, a schema-mandatory attribute, so the
    partition pruner can prove never-match before lowering); a small
    fraction is org-wide (core groups, resident in every partition);
  * **edit stability** — every policy has its own filename + policy id
    and a per-policy derived RNG, so replacing one policy leaves every
    other Policy OBJECT (and its cached content fingerprint) untouched:
    exactly the CRD-store reload shape the shard differ keys on.

Determinism: ``synth_corpus(n, seed, clusters)`` twice yields identical
sources; per-policy parameters derive from ``Random((seed, i))``, never
from a shared stream, so an edit cannot reshuffle its neighbors.

The corpus also synthesizes matched traffic: ``sar_items``/``sar_bodies``
draw requests that hit the generated policies of ONE cluster (the
partition a serving process owns), and ``probe_request`` targets the
dedicated probe policy whose effect ``with_edit()`` flips — the
single-policy CRD edit the <1s edit-to-serving gate measures.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..lang.authorize import PolicySet
from ..lang.parser import parse_policies

CORE_GROUPS = ("", "apps", "rbac.authorization.k8s.io")
RESOURCES = (
    "pods", "services", "secrets", "configmaps", "deployments",
    "jobs", "statefulsets", "daemonsets", "cronjobs", "endpoints",
)
VERBS = ("get", "list", "watch", "create", "update", "delete", "patch")

PROBE_USER = "probe-user"
PROBE_RESOURCE = "probes"


def _cluster_groups(cluster: int, tenant: str = "") -> Tuple[str, ...]:
    # a tenant tag namespaces the cluster-local groups: multi-tenant
    # corpora get DISJOINT apiGroup universes per tenant (cross-tenant
    # content can never accidentally match), while CORE_GROUPS stay
    # shared org-wide — the slice that makes the isolation differential
    # sharp (without discriminators, tenant B's org-wide policies WOULD
    # flip tenant A's core-group decisions)
    tag = f"{tenant}." if tenant else ""
    return (
        f"platform.{tag}c{cluster}.corp",
        f"data.{tag}c{cluster}.corp",
        f"ml.{tag}c{cluster}.corp",
    )


@dataclass
class _PolicyParams:
    """The request-relevant parameters one synthesized policy was built
    from — retained so traffic synthesis can aim at real policies without
    re-parsing anything."""

    kind: str
    cluster: int  # -1 = org-wide (core groups)
    group: str
    team: str = ""
    user: str = ""
    ns: str = ""
    resource: str = ""
    verbs: Tuple[str, ...] = ()


def _policy_source(
    i: int, seed: int, clusters: int, tenant: str = ""
) -> Tuple[str, _PolicyParams]:
    rng = random.Random(f"{seed}:{i}")
    cluster = i % clusters
    org_wide = rng.random() < 0.02
    if org_wide:
        group = rng.choice(CORE_GROUPS)
        cluster = -1
    else:
        group = rng.choice(_cluster_groups(cluster, tenant))
    prefix = "org" if org_wide else f"c{cluster}"
    team = f"{prefix}-team-{rng.randint(0, 99)}"
    user = f"{prefix}-user-{rng.randint(0, 499)}"
    ns = f"{prefix}-ns-{rng.randint(0, 199)}"
    res = rng.choice(RESOURCES)
    verbs = tuple(rng.sample(VERBS, rng.randint(1, 3)))
    acts = ", ".join(f'k8s::Action::"{v}"' for v in verbs)
    kind = rng.random()
    if kind < 0.55:
        src = (
            f'permit (principal in k8s::Group::"{team}", action in [{acts}], '
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "{res}" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "team", cluster, group, team=team, ns=ns, resource=res,
            verbs=verbs,
        )
    elif kind < 0.75:
        src = (
            f"permit (principal is k8s::User, action in [{acts}], "
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'principal.name == "{user}" && '
            f'resource.resource == "{res}" }};'
        )
        params = _PolicyParams(
            "user", cluster, group, user=user, resource=res, verbs=verbs
        )
    elif kind < 0.9:
        src = (
            "permit (principal, action in [k8s::Action::\"get\", "
            'k8s::Action::"list", k8s::Action::"watch"], '
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "{res}" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "read", cluster, group, ns=ns, resource=res,
            verbs=("get", "list", "watch"),
        )
    else:
        src = (
            f"forbid (principal, action in [{acts}], "
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "secrets" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "forbid", cluster, group, ns=ns, resource="secrets", verbs=verbs
        )
    return src, params


def _probe_source(effect: str, tenant: str = "") -> str:
    group = _cluster_groups(0, tenant)[0]
    return (
        f'{effect} (principal is k8s::User, action == k8s::Action::"get", '
        "resource is k8s::Resource) when { "
        f'resource.apiGroup == "{group}" && '
        f'principal.name == "{PROBE_USER}" && '
        f'resource.resource == "{PROBE_RESOURCE}" }};'
    )


@dataclass
class SynthCorpus:
    policies: List[object]  # parsed lang.ast.Policy, one filename each
    params: List[_PolicyParams]
    n: int
    seed: int
    clusters: int
    probe_index: int = 0
    probe_effect: str = "permit"
    # multi-tenant corpora (synth_tenant_corpora): the tenant tag that
    # namespaces this corpus's cluster-local apiGroups — "" keeps every
    # generated byte identical to the single-tenant form
    tenant: str = ""
    _tier_cache: Optional[List[PolicySet]] = field(default=None, repr=False)

    # ----------------------------------------------------------- policy side

    def tiers(self) -> List[PolicySet]:
        """The corpus as a single-tier stack (cached: repeated loads must
        hand the engine IDENTICAL Policy objects, like a store would)."""
        if self._tier_cache is None:
            self._tier_cache = [PolicySet(list(self.policies))]
        return self._tier_cache

    def with_edit(self, index: Optional[int] = None) -> "SynthCorpus":
        """The corpus after one single-policy CRD edit: by default the
        probe policy's effect flips (permit <-> forbid), re-parsed alone
        under its own filename — every OTHER Policy object is shared by
        identity with this corpus, exactly like a CRD-store relist that
        reparses one changed object."""
        idx = self.probe_index if index is None else index
        effect = self.probe_effect
        if idx == self.probe_index:
            effect = "forbid" if effect == "permit" else "permit"
            src = _probe_source(effect, self.tenant)
        else:
            src, _ = _policy_source(idx, self.seed, self.clusters, self.tenant)
            # flip WHICHEVER effect the policy has — a permit-only
            # replace on a forbid-kind policy would be a silent no-op
            # edit (identical corpus, dirty_shards == 0) and fail far
            # from the cause
            if src.startswith("permit "):
                src = "forbid " + src[len("permit "):]
            elif src.startswith("forbid "):
                src = "permit " + src[len("forbid "):]
            else:  # unreachable for generated sources; fail loudly
                raise ValueError(f"with_edit: unrecognized effect in {src[:40]!r}")
        old = self.policies[idx]
        p = parse_policies(src, old.filename)[0]
        p.policy_id = old.policy_id
        pols = list(self.policies)
        pols[idx] = p
        return SynthCorpus(
            policies=pols,
            params=self.params,
            n=self.n,
            seed=self.seed,
            clusters=self.clusters,
            probe_index=self.probe_index,
            probe_effect=effect,
            tenant=self.tenant,
        )

    def partition_dict(self, cluster: int) -> dict:
        """The serving-partition spec for one cluster: its API groups
        plus the org-wide core groups."""
        return {
            "name": f"cluster-{cluster}",
            "slots": {
                "resource.apiGroup": list(
                    CORE_GROUPS + _cluster_groups(cluster, self.tenant)
                ),
            },
        }

    def spec(self, cluster: int):
        from ..analysis.partition import PartitionSpec

        return PartitionSpec.from_dict(self.partition_dict(cluster))

    # ---------------------------------------------------------- traffic side

    def _attrs(self, rng: random.Random, cluster: int):
        """One in-partition SAR's attributes, aimed at the generated
        policies: ~80% target a known policy's (group, resource, ns,
        verb), the rest draw in-universe misses."""
        from ..entities.attributes import Attributes, UserInfo

        cluster_params = [
            p
            for p in self.params
            if p.cluster in (cluster, -1) and p.kind != "probe"
        ]
        if cluster_params and rng.random() < 0.8:
            p = rng.choice(cluster_params)
            user = p.user or f"c{cluster}-user-{rng.randint(0, 499)}"
            groups: Tuple[str, ...] = (p.team,) if p.team else ()
            return Attributes(
                user=UserInfo(name=user, uid="u", groups=groups),
                verb=rng.choice(p.verbs or VERBS),
                namespace=p.ns or f"c{cluster}-ns-{rng.randint(0, 199)}",
                api_group=p.group,
                api_version="v1",
                resource=p.resource or rng.choice(RESOURCES),
                resource_request=True,
                # tenant-tagged corpora stamp their traffic too, so
                # sar_items feed a fused plane directly; "" is a no-op
                tenant=self.tenant,
            )
        group = rng.choice(
            CORE_GROUPS + _cluster_groups(cluster, self.tenant)
        )
        return Attributes(
            user=UserInfo(
                name=f"c{cluster}-user-{rng.randint(0, 499)}",
                uid="u",
                groups=(f"c{cluster}-team-{rng.randint(0, 99)}",),
            ),
            verb=rng.choice(VERBS),
            namespace=f"c{cluster}-ns-{rng.randint(0, 199)}",
            api_group=group,
            api_version="v1",
            resource=rng.choice(RESOURCES),
            resource_request=True,
            tenant=self.tenant,
        )

    def sar_items(self, n: int, cluster: int = 0, seed: int = 1) -> list:
        """n (EntityMap, Request) pairs of in-partition traffic."""
        from ..server.authorizer import record_to_cedar_resource

        rng = random.Random(f"{self.seed}:sar:{seed}:{cluster}")
        return [
            record_to_cedar_resource(self._attrs(rng, cluster))
            for _ in range(n)
        ]

    def sar_bodies(self, n: int, cluster: int = 0, seed: int = 1) -> list:
        """n raw SubjectAccessReview JSON bodies (webhook wire shape)."""
        rng = random.Random(f"{self.seed}:sar:{seed}:{cluster}")
        out = []
        for _ in range(n):
            a = self._attrs(rng, cluster)
            out.append(
                json.dumps(
                    {
                        "apiVersion": "authorization.k8s.io/v1",
                        "kind": "SubjectAccessReview",
                        "spec": {
                            "user": a.user.name,
                            "uid": "u",
                            "groups": list(a.user.groups),
                            "resourceAttributes": {
                                "verb": a.verb,
                                "group": a.api_group,
                                "version": "v1",
                                "resource": a.resource,
                                "namespace": a.namespace,
                            },
                        },
                    }
                ).encode()
            )
        return out

    def probe_request(self):
        """(EntityMap, Request) matching exactly the probe policy."""
        from ..entities.attributes import Attributes, UserInfo
        from ..server.authorizer import record_to_cedar_resource

        return record_to_cedar_resource(
            Attributes(
                user=UserInfo(name=PROBE_USER, uid="u", groups=()),
                verb="get",
                namespace="c0-ns-0",
                api_group=_cluster_groups(0, self.tenant)[0],
                api_version="v1",
                resource=PROBE_RESOURCE,
                resource_request=True,
                tenant=self.tenant,
            )
        )


def synth_corpus(
    n: int,
    seed: int = 0,
    clusters: int = 10,
    filename_prefix: str = "synth",
    tenant: str = "",
) -> SynthCorpus:
    """Synthesize an ``n``-policy org corpus spread over ``clusters``
    clusters (index 0 carries the probe policy). One combined parse keeps
    generation fast; each policy then gets its own filename + stable id
    so edits and shard bucketing behave like per-object CRD stores.
    ``tenant`` tags the cluster-local apiGroups (multi-tenant corpora,
    see synth_tenant_corpora); "" is byte-identical to before."""
    if n < 1:
        raise ValueError("synth_corpus: n must be >= 1")
    if clusters < 1:
        raise ValueError("synth_corpus: clusters must be >= 1")
    srcs = [_probe_source("permit", tenant)]
    params: List[_PolicyParams] = [
        _PolicyParams("probe", 0, _cluster_groups(0, tenant)[0])
    ]
    for i in range(1, n):
        src, p = _policy_source(i, seed, clusters, tenant)
        srcs.append(src)
        params.append(p)
    policies = parse_policies("\n".join(srcs), filename_prefix)
    for i, p in enumerate(policies):
        p.policy_id = f"{filename_prefix}-{i:06d}"
        p.filename = f"{filename_prefix}-{i:06d}.cedar"
    return SynthCorpus(
        policies=list(policies),
        params=params,
        n=n,
        seed=seed,
        clusters=clusters,
        probe_index=0,
        probe_effect="permit",
        tenant=tenant,
    )


def synth_tenant_corpora(
    n: int, tenants: int, seed: int = 0, clusters: int = 4
) -> "Dict[str, SynthCorpus]":
    """``tenants`` deterministic per-tenant corpora of ``n`` policies each
    (ordered dict: tenant id → corpus) — the multi-tenant bench/test
    generator (bench.py --tenants, tests/test_tenancy.py).

    Per-tenant DERIVED seeds (never the shared stream, so one tenant's
    regeneration can't reshuffle a neighbor), DISJOINT cluster-local
    apiGroup universes (the tenant tag in _cluster_groups), and one
    shared org-wide slice (CORE_GROUPS policies, ~2%) that overlaps
    across tenants — the content that would cross-match without the
    plane's tenant discriminators. Policy ids/filenames are prefixed by
    tenant, so the fused plane's shard-scoped cache stamps resolve
    per-tenant."""
    if tenants < 1:
        raise ValueError("synth_tenant_corpora: tenants must be >= 1")
    out: Dict[str, SynthCorpus] = {}
    for t in range(tenants):
        tid = f"tenant-{t:02d}"
        tseed = random.Random(f"{seed}:tenant:{tid}").randrange(1 << 31)
        out[tid] = synth_corpus(
            n, seed=tseed, clusters=clusters, filename_prefix=tid,
            tenant=tid,
        )
    return out
