"""Seeded synthesis of realistic org-wide (multi-cluster) policy sets.

The scale story (ROADMAP open item 4 / docs/performance.md "Giant policy
sets") needs corpora with two properties real 100k-rule org stores have
and the 10k bench generator lacks:

  * **cluster locality** — most policies target ONE cluster's API groups
    (the serving-partition discriminator rides ``resource.apiGroup`` as
    the first ``when`` conjunct, a schema-mandatory attribute, so the
    partition pruner can prove never-match before lowering); a small
    fraction is org-wide (core groups, resident in every partition);
  * **edit stability** — every policy has its own filename + policy id
    and a per-policy derived RNG, so replacing one policy leaves every
    other Policy OBJECT (and its cached content fingerprint) untouched:
    exactly the CRD-store reload shape the shard differ keys on.

Determinism: ``synth_corpus(n, seed, clusters)`` twice yields identical
sources; per-policy parameters derive from ``Random((seed, i))``, never
from a shared stream, so an edit cannot reshuffle its neighbors.

The corpus also synthesizes matched traffic: ``sar_items``/``sar_bodies``
draw requests that hit the generated policies of ONE cluster (the
partition a serving process owns), and ``probe_request`` targets the
dedicated probe policy whose effect ``with_edit()`` flips — the
single-policy CRD edit the <1s edit-to-serving gate measures.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..lang.authorize import PolicySet
from ..lang.parser import parse_policies

CORE_GROUPS = ("", "apps", "rbac.authorization.k8s.io")
RESOURCES = (
    "pods", "services", "secrets", "configmaps", "deployments",
    "jobs", "statefulsets", "daemonsets", "cronjobs", "endpoints",
)
VERBS = ("get", "list", "watch", "create", "update", "delete", "patch")

PROBE_USER = "probe-user"
PROBE_RESOURCE = "probes"


def _cluster_groups(cluster: int, tenant: str = "") -> Tuple[str, ...]:
    # a tenant tag namespaces the cluster-local groups: multi-tenant
    # corpora get DISJOINT apiGroup universes per tenant (cross-tenant
    # content can never accidentally match), while CORE_GROUPS stay
    # shared org-wide — the slice that makes the isolation differential
    # sharp (without discriminators, tenant B's org-wide policies WOULD
    # flip tenant A's core-group decisions)
    tag = f"{tenant}." if tenant else ""
    return (
        f"platform.{tag}c{cluster}.corp",
        f"data.{tag}c{cluster}.corp",
        f"ml.{tag}c{cluster}.corp",
    )


@dataclass
class _PolicyParams:
    """The request-relevant parameters one synthesized policy was built
    from — retained so traffic synthesis can aim at real policies without
    re-parsing anything."""

    kind: str
    cluster: int  # -1 = org-wide (core groups)
    group: str
    team: str = ""
    user: str = ""
    ns: str = ""
    resource: str = ""
    verbs: Tuple[str, ...] = ()


def _policy_source(
    i: int, seed: int, clusters: int, tenant: str = ""
) -> Tuple[str, _PolicyParams]:
    rng = random.Random(f"{seed}:{i}")
    cluster = i % clusters
    org_wide = rng.random() < 0.02
    if org_wide:
        group = rng.choice(CORE_GROUPS)
        cluster = -1
    else:
        group = rng.choice(_cluster_groups(cluster, tenant))
    prefix = "org" if org_wide else f"c{cluster}"
    team = f"{prefix}-team-{rng.randint(0, 99)}"
    user = f"{prefix}-user-{rng.randint(0, 499)}"
    ns = f"{prefix}-ns-{rng.randint(0, 199)}"
    res = rng.choice(RESOURCES)
    verbs = tuple(rng.sample(VERBS, rng.randint(1, 3)))
    acts = ", ".join(f'k8s::Action::"{v}"' for v in verbs)
    kind = rng.random()
    if kind < 0.55:
        src = (
            f'permit (principal in k8s::Group::"{team}", action in [{acts}], '
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "{res}" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "team", cluster, group, team=team, ns=ns, resource=res,
            verbs=verbs,
        )
    elif kind < 0.75:
        src = (
            f"permit (principal is k8s::User, action in [{acts}], "
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'principal.name == "{user}" && '
            f'resource.resource == "{res}" }};'
        )
        params = _PolicyParams(
            "user", cluster, group, user=user, resource=res, verbs=verbs
        )
    elif kind < 0.9:
        src = (
            "permit (principal, action in [k8s::Action::\"get\", "
            'k8s::Action::"list", k8s::Action::"watch"], '
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "{res}" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "read", cluster, group, ns=ns, resource=res,
            verbs=("get", "list", "watch"),
        )
    else:
        src = (
            f"forbid (principal, action in [{acts}], "
            "resource is k8s::Resource) when { "
            f'resource.apiGroup == "{group}" && '
            f'resource.resource == "secrets" && '
            "resource has namespace && "
            f'resource.namespace == "{ns}" }};'
        )
        params = _PolicyParams(
            "forbid", cluster, group, ns=ns, resource="secrets", verbs=verbs
        )
    return src, params


def _probe_source(effect: str, tenant: str = "") -> str:
    group = _cluster_groups(0, tenant)[0]
    return (
        f'{effect} (principal is k8s::User, action == k8s::Action::"get", '
        "resource is k8s::Resource) when { "
        f'resource.apiGroup == "{group}" && '
        f'principal.name == "{PROBE_USER}" && '
        f'resource.resource == "{PROBE_RESOURCE}" }};'
    )


@dataclass
class SynthCorpus:
    policies: List[object]  # parsed lang.ast.Policy, one filename each
    params: List[_PolicyParams]
    n: int
    seed: int
    clusters: int
    probe_index: int = 0
    probe_effect: str = "permit"
    # multi-tenant corpora (synth_tenant_corpora): the tenant tag that
    # namespaces this corpus's cluster-local apiGroups — "" keeps every
    # generated byte identical to the single-tenant form
    tenant: str = ""
    _tier_cache: Optional[List[PolicySet]] = field(default=None, repr=False)

    # ----------------------------------------------------------- policy side

    def tiers(self) -> List[PolicySet]:
        """The corpus as a single-tier stack (cached: repeated loads must
        hand the engine IDENTICAL Policy objects, like a store would)."""
        if self._tier_cache is None:
            self._tier_cache = [PolicySet(list(self.policies))]
        return self._tier_cache

    def with_edit(self, index: Optional[int] = None) -> "SynthCorpus":
        """The corpus after one single-policy CRD edit: by default the
        probe policy's effect flips (permit <-> forbid), re-parsed alone
        under its own filename — every OTHER Policy object is shared by
        identity with this corpus, exactly like a CRD-store relist that
        reparses one changed object."""
        idx = self.probe_index if index is None else index
        effect = self.probe_effect
        if idx == self.probe_index:
            effect = "forbid" if effect == "permit" else "permit"
            src = _probe_source(effect, self.tenant)
        else:
            src, _ = _policy_source(idx, self.seed, self.clusters, self.tenant)
            # flip WHICHEVER effect the policy has — a permit-only
            # replace on a forbid-kind policy would be a silent no-op
            # edit (identical corpus, dirty_shards == 0) and fail far
            # from the cause
            if src.startswith("permit "):
                src = "forbid " + src[len("permit "):]
            elif src.startswith("forbid "):
                src = "permit " + src[len("forbid "):]
            else:  # unreachable for generated sources; fail loudly
                raise ValueError(f"with_edit: unrecognized effect in {src[:40]!r}")
        old = self.policies[idx]
        p = parse_policies(src, old.filename)[0]
        p.policy_id = old.policy_id
        pols = list(self.policies)
        pols[idx] = p
        return SynthCorpus(
            policies=pols,
            params=self.params,
            n=self.n,
            seed=self.seed,
            clusters=self.clusters,
            probe_index=self.probe_index,
            probe_effect=effect,
            tenant=self.tenant,
        )

    def partition_dict(self, cluster: int) -> dict:
        """The serving-partition spec for one cluster: its API groups
        plus the org-wide core groups."""
        return {
            "name": f"cluster-{cluster}",
            "slots": {
                "resource.apiGroup": list(
                    CORE_GROUPS + _cluster_groups(cluster, self.tenant)
                ),
            },
        }

    def spec(self, cluster: int):
        from ..analysis.partition import PartitionSpec

        return PartitionSpec.from_dict(self.partition_dict(cluster))

    # ---------------------------------------------------------- traffic side

    def _attrs(self, rng: random.Random, cluster: int):
        """One in-partition SAR's attributes, aimed at the generated
        policies: ~80% target a known policy's (group, resource, ns,
        verb), the rest draw in-universe misses."""
        from ..entities.attributes import Attributes, UserInfo

        cluster_params = [
            p
            for p in self.params
            if p.cluster in (cluster, -1) and p.kind != "probe"
        ]
        if cluster_params and rng.random() < 0.8:
            p = rng.choice(cluster_params)
            user = p.user or f"c{cluster}-user-{rng.randint(0, 499)}"
            groups: Tuple[str, ...] = (p.team,) if p.team else ()
            return Attributes(
                user=UserInfo(name=user, uid="u", groups=groups),
                verb=rng.choice(p.verbs or VERBS),
                namespace=p.ns or f"c{cluster}-ns-{rng.randint(0, 199)}",
                api_group=p.group,
                api_version="v1",
                resource=p.resource or rng.choice(RESOURCES),
                resource_request=True,
                # tenant-tagged corpora stamp their traffic too, so
                # sar_items feed a fused plane directly; "" is a no-op
                tenant=self.tenant,
            )
        group = rng.choice(
            CORE_GROUPS + _cluster_groups(cluster, self.tenant)
        )
        return Attributes(
            user=UserInfo(
                name=f"c{cluster}-user-{rng.randint(0, 499)}",
                uid="u",
                groups=(f"c{cluster}-team-{rng.randint(0, 99)}",),
            ),
            verb=rng.choice(VERBS),
            namespace=f"c{cluster}-ns-{rng.randint(0, 199)}",
            api_group=group,
            api_version="v1",
            resource=rng.choice(RESOURCES),
            resource_request=True,
            tenant=self.tenant,
        )

    def sar_items(self, n: int, cluster: int = 0, seed: int = 1) -> list:
        """n (EntityMap, Request) pairs of in-partition traffic."""
        from ..server.authorizer import record_to_cedar_resource

        rng = random.Random(f"{self.seed}:sar:{seed}:{cluster}")
        return [
            record_to_cedar_resource(self._attrs(rng, cluster))
            for _ in range(n)
        ]

    def sar_bodies(self, n: int, cluster: int = 0, seed: int = 1) -> list:
        """n raw SubjectAccessReview JSON bodies (webhook wire shape)."""
        rng = random.Random(f"{self.seed}:sar:{seed}:{cluster}")
        out = []
        for _ in range(n):
            a = self._attrs(rng, cluster)
            out.append(
                json.dumps(
                    {
                        "apiVersion": "authorization.k8s.io/v1",
                        "kind": "SubjectAccessReview",
                        "spec": {
                            "user": a.user.name,
                            "uid": "u",
                            "groups": list(a.user.groups),
                            "resourceAttributes": {
                                "verb": a.verb,
                                "group": a.api_group,
                                "version": "v1",
                                "resource": a.resource,
                                "namespace": a.namespace,
                            },
                        },
                    }
                ).encode()
            )
        return out

    def probe_request(self):
        """(EntityMap, Request) matching exactly the probe policy."""
        from ..entities.attributes import Attributes, UserInfo
        from ..server.authorizer import record_to_cedar_resource

        return record_to_cedar_resource(
            Attributes(
                user=UserInfo(name=PROBE_USER, uid="u", groups=()),
                verb="get",
                namespace="c0-ns-0",
                api_group=_cluster_groups(0, self.tenant)[0],
                api_version="v1",
                resource=PROBE_RESOURCE,
                resource_request=True,
                tenant=self.tenant,
            )
        )


def synth_corpus(
    n: int,
    seed: int = 0,
    clusters: int = 10,
    filename_prefix: str = "synth",
    tenant: str = "",
) -> SynthCorpus:
    """Synthesize an ``n``-policy org corpus spread over ``clusters``
    clusters (index 0 carries the probe policy). One combined parse keeps
    generation fast; each policy then gets its own filename + stable id
    so edits and shard bucketing behave like per-object CRD stores.
    ``tenant`` tags the cluster-local apiGroups (multi-tenant corpora,
    see synth_tenant_corpora); "" is byte-identical to before."""
    if n < 1:
        raise ValueError("synth_corpus: n must be >= 1")
    if clusters < 1:
        raise ValueError("synth_corpus: clusters must be >= 1")
    srcs = [_probe_source("permit", tenant)]
    params: List[_PolicyParams] = [
        _PolicyParams("probe", 0, _cluster_groups(0, tenant)[0])
    ]
    for i in range(1, n):
        src, p = _policy_source(i, seed, clusters, tenant)
        srcs.append(src)
        params.append(p)
    policies = parse_policies("\n".join(srcs), filename_prefix)
    for i, p in enumerate(policies):
        p.policy_id = f"{filename_prefix}-{i:06d}"
        p.filename = f"{filename_prefix}-{i:06d}.cedar"
    return SynthCorpus(
        policies=list(policies),
        params=params,
        n=n,
        seed=seed,
        clusters=clusters,
        probe_index=0,
        probe_effect="permit",
        tenant=tenant,
    )


# --------------------------------------------------------------- coverage
# Adversarial lowerability corpus (ROADMAP item 3 / bench.py --coverage):
# every Unlowerable family the burn-down tracks, generated deterministically
# against the same schema-generator/RBAC-converter shapes as the scale
# corpus, plus matched traffic that exercises each family's match, miss,
# presence-guard, and error paths.

# family -> what the full compiler does with it
COVERAGE_FAMILIES = (
    "spill",            # DNF expansion past MAX_CLAUSES: lowers via spillover
    "negated_untyped",  # negated like/cmp/contains on untyped context attrs:
                        # lowers via TYPE_ERR guards + clause flow-typing
    "ancestor_in",      # attr-chain `in` over deep ancestor graphs: lowers
                        # to IN_SLOT closure literals
    "opaque",           # negated arithmetic/ext exprs: lowers via the
                        # host-guardable HARD_OK path
    "blowup",           # expansion past SPILL_MAX_CLAUSES: still fallback
)

_COV_CHANNELS = ("beta", "stable", "canary", "dev")
_COV_CHAIN_DEPTH = 16  # parent-chain length behind each coverage root group


def _coverage_policy(
    i: int, family: str, seed: int, clusters: int
) -> Tuple[str, _PolicyParams]:
    """One adversarial policy of ``family``, scoped like a real cluster
    policy (apiGroup discriminator first, the schema-generator shape)."""
    rng = random.Random(f"{seed}:cov:{family}:{i}")
    cluster = i % clusters
    group = rng.choice(_cluster_groups(cluster))
    res = rng.choice(RESOURCES)
    scope = (
        f'resource.apiGroup == "{group}" && resource.resource == "{res}"'
    )
    params = _PolicyParams(f"cov-{family}", cluster, group, resource=res,
                           verbs=VERBS)
    if family in ("spill", "blowup"):
        # alternation product: ==-chains stay linear per slot (exclusivity
        # simplification), so clauses multiply ACROSS slots. 12x12=144
        # raw clauses clears MAX_CLAUSES=96 (spillover territory);
        # 13x13x13=2197 clears SPILL_MAX_CLAUSES=2048 (genuine fallback).
        per = 13 if family == "blowup" else 12
        names = " || ".join(
            f'resource.name == "cov-n{rng.randint(0, 7)}-{j}"'
            for j in range(per)
        )
        nss = " || ".join(
            f'resource.namespace == "cov-ns{rng.randint(0, 7)}-{j}"'
            for j in range(per)
        )
        body = f"({names}) && ({nss})"
        if family == "blowup":
            subs = " || ".join(
                f'resource.subresource == "cov-s-{j}"' for j in range(per)
            )
            body += f" && ({subs})"
        src = (
            "permit (principal, action, resource is k8s::Resource) "
            f"when {{ {scope} && ({body}) }};"
        )
    elif family == "negated_untyped":
        shape = rng.randrange(3)
        if shape == 0:
            neg = f'context.channel like "{rng.choice(_COV_CHANNELS)}*"'
        elif shape == 1:
            neg = f"context.build < {rng.randint(10, 99)}"
        else:
            neg = f'context.tags.contains("restricted-{rng.randint(0, 3)}")'
        src = (
            "permit (principal, action, resource is k8s::Resource) "
            f"when {{ {scope} }} unless {{ {neg} }};"
        )
    elif family == "ancestor_in":
        root = f"cov-root-{rng.randint(0, 3)}"
        kw = "unless" if rng.random() < 0.3 else "when"
        cond = f'context.team in k8s::Group::"{root}"'
        if kw == "when":
            src = (
                "permit (principal, action, resource is k8s::Resource) "
                f"when {{ {scope} && {cond} }};"
            )
        else:
            src = (
                "permit (principal, action, resource is k8s::Resource) "
                f"when {{ {scope} }} unless {{ {cond} }};"
            )
    elif family == "opaque":
        shape = rng.randrange(3)
        if shape == 0:
            neg = f"context.n + 1 == {rng.randint(2, 9)}"
        elif shape == 1:
            neg = f"context.a * 2 < context.b"
        else:
            neg = "ip(context.addr).isLoopback()"
        src = (
            "permit (principal, action, resource is k8s::Resource) "
            f"when {{ {scope} }} unless {{ {neg} }};"
        )
    else:
        raise ValueError(f"unknown coverage family {family!r}")
    return src, params


@dataclass
class CoverageCorpus:
    """The adversarial corpus plus its matched traffic. ``families`` maps
    each family name to the policy ids generated for it, so benches and
    tests can assert per-family lowering outcomes."""

    policies: List[object]
    params: List[_PolicyParams]
    families: Dict[str, List[str]]
    seed: int
    clusters: int
    _tier_cache: Optional[List[PolicySet]] = field(default=None, repr=False)

    def tiers(self) -> List[PolicySet]:
        if self._tier_cache is None:
            self._tier_cache = [PolicySet(list(self.policies))]
        return self._tier_cache

    def chain_entities(self):
        """The deep ancestor chains behind the ancestor_in roots: each
        root group ``cov-root-k`` sits atop a ``_COV_CHAIN_DEPTH``-deep
        parent chain; traffic teams enter at the chain bottom."""
        from ..lang.entities import Entity
        from ..lang.values import EntityUID

        ents = []
        for k in range(4):
            chain = [f"cov-root-{k}"] + [
                f"cov-mid-{k}-{d}" for d in range(_COV_CHAIN_DEPTH)
            ]
            for child, parent in zip(chain[1:], chain[:-1]):
                ents.append(
                    Entity(
                        EntityUID("k8s::Group", child),
                        parents=(EntityUID("k8s::Group", parent),),
                    )
                )
        return ents

    def _context(self, rng: random.Random):
        """One request context drawing every family's keys with mixed
        types: matches, misses, absent keys (presence-guard paths), and
        wrong-typed values (the TYPE_ERR / guard-error paths)."""
        from ..lang.values import CedarRecord, CedarSet, EntityUID

        ctx: Dict[str, object] = {}
        r = rng.random()
        if r < 0.7:
            ctx["channel"] = (
                f"{rng.choice(_COV_CHANNELS)}-{rng.randint(0, 9)}"
            )
        elif r < 0.85:
            ctx["channel"] = rng.randint(0, 9)  # type error under `like`
        if rng.random() < 0.8:
            ctx["build"] = (
                rng.randint(0, 120) if rng.random() < 0.85 else "not-a-long"
            )
        if rng.random() < 0.8:
            ctx["tags"] = (
                CedarSet(
                    [f"restricted-{rng.randint(0, 5)}", "public"]
                )
                if rng.random() < 0.85
                else "restricted-0"  # type error under .contains
            )
        r = rng.random()
        if r < 0.6:
            k, d = rng.randint(0, 3), rng.randint(0, _COV_CHAIN_DEPTH - 1)
            ctx["team"] = EntityUID("k8s::Group", f"cov-mid-{k}-{d}")
        elif r < 0.75:
            ctx["team"] = EntityUID("k8s::Group", f"other-{rng.randint(0, 3)}")
        elif r < 0.85:
            ctx["team"] = "not-an-entity"  # type error under `in`
        if rng.random() < 0.8:
            ctx["n"] = rng.randint(0, 9)
        if rng.random() < 0.8:
            ctx["a"] = rng.randint(0, 9)
            ctx["b"] = rng.randint(0, 20)
        r = rng.random()
        if r < 0.5:
            ctx["addr"] = rng.choice(("127.0.0.1", "10.1.2.3", "::1"))
        elif r < 0.7:
            ctx["addr"] = "not-an-ip"  # guard-error path
        return CedarRecord(ctx)

    def items(self, n: int, seed: int = 1) -> list:
        """n (EntityMap, Request) pairs aimed at the corpus: SAR-shaped
        resource/principal attributes targeting the generated policies'
        (group, resource, name, namespace) universe, contexts drawing
        every family's keys, and the deep group chains merged into each
        entity map."""
        from ..entities.attributes import Attributes, UserInfo
        from ..lang.eval import Request
        from ..server.authorizer import record_to_cedar_resource

        rng = random.Random(f"{self.seed}:covsar:{seed}")
        chain = self.chain_entities()
        out = []
        for _ in range(n):
            p = rng.choice(self.params)
            a = Attributes(
                user=UserInfo(
                    name=f"cov-user-{rng.randint(0, 49)}",
                    uid="u",
                    groups=(f"cov-team-{rng.randint(0, 9)}",),
                ),
                verb=rng.choice(VERBS),
                namespace=f"cov-ns{rng.randint(0, 7)}-{rng.randint(0, 13)}",
                api_group=p.group if rng.random() < 0.8 else "other.corp",
                api_version="v1",
                resource=p.resource or rng.choice(RESOURCES),
                name=f"cov-n{rng.randint(0, 7)}-{rng.randint(0, 13)}",
                resource_request=True,
            )
            em, req = record_to_cedar_resource(a)
            for e in chain:
                em.add(e)
            out.append(
                (em, Request(req.principal, req.action, req.resource,
                             self._context(rng)))
            )
        return out


def coverage_corpus(
    per_family: int = 4,
    base: int = 24,
    seed: int = 0,
    clusters: int = 4,
    filename_prefix: str = "cov",
) -> CoverageCorpus:
    """The adversarial lowerability corpus: ``base`` realistic policies
    (the scale generator's shapes) + ``per_family`` policies of each
    COVERAGE_FAMILIES entry, deterministically derived from ``seed``.
    Coverage numbers measured on it answer "what fraction of a realistic
    set with THESE constructs serves from the device plane?"."""
    if per_family < 1:
        raise ValueError("coverage_corpus: per_family must be >= 1")
    srcs: List[str] = []
    params: List[_PolicyParams] = []
    fam_of: List[str] = []
    for i in range(base):
        src, p = _policy_source(i + 1, seed, clusters)
        srcs.append(src)
        params.append(p)
        fam_of.append("base")
    for family in COVERAGE_FAMILIES:
        for i in range(per_family):
            src, p = _coverage_policy(i, family, seed, clusters)
            srcs.append(src)
            params.append(p)
            fam_of.append(family)
    policies = parse_policies("\n".join(srcs), filename_prefix)
    if len(policies) != len(srcs):
        raise RuntimeError("coverage_corpus: parse produced a policy-count "
                           f"mismatch ({len(policies)} != {len(srcs)})")
    families: Dict[str, List[str]] = {f: [] for f in COVERAGE_FAMILIES}
    families["base"] = []
    for i, p in enumerate(policies):
        p.policy_id = f"{filename_prefix}-{fam_of[i]}-{i:04d}"
        p.filename = f"{filename_prefix}-{i:04d}.cedar"
        families[fam_of[i]].append(p.policy_id)
    return CoverageCorpus(
        policies=list(policies),
        params=params,
        families=families,
        seed=seed,
        clusters=clusters,
    )


def synth_tenant_corpora(
    n: int, tenants: int, seed: int = 0, clusters: int = 4
) -> "Dict[str, SynthCorpus]":
    """``tenants`` deterministic per-tenant corpora of ``n`` policies each
    (ordered dict: tenant id → corpus) — the multi-tenant bench/test
    generator (bench.py --tenants, tests/test_tenancy.py).

    Per-tenant DERIVED seeds (never the shared stream, so one tenant's
    regeneration can't reshuffle a neighbor), DISJOINT cluster-local
    apiGroup universes (the tenant tag in _cluster_groups), and one
    shared org-wide slice (CORE_GROUPS policies, ~2%) that overlaps
    across tenants — the content that would cross-match without the
    plane's tenant discriminators. Policy ids/filenames are prefixed by
    tenant, so the fused plane's shard-scoped cache stamps resolve
    per-tenant."""
    if tenants < 1:
        raise ValueError("synth_tenant_corpora: tenants must be >= 1")
    out: Dict[str, SynthCorpus] = {}
    for t in range(tenants):
        tid = f"tenant-{t:02d}"
        tseed = random.Random(f"{seed}:tenant:{tid}").randrange(1 << 31)
        out[tid] = synth_corpus(
            n, seed=tseed, clusters=clusters, filename_prefix=tid,
            tenant=tid,
        )
    return out
