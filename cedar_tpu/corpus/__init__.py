"""Deterministic synthesis of org-scale policy corpora and traffic.

A GENERATOR, not fixtures: ``bench.py --scale`` and the shard-diff tests
(tests/test_scale.py) both synthesize their corpora from a seed at run
time, so nothing multi-megabyte is checked in and every corpus is
reproducible from (n, seed, clusters).
"""

from .synth import SynthCorpus, synth_corpus, synth_tenant_corpora

__all__ = ["SynthCorpus", "synth_corpus", "synth_tenant_corpora"]
