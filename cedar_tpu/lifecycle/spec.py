"""PolicyRollout specs: the declarative input to the lifecycle controller.

A spec names ONE tenant's candidate source, the ordered evidence gates
(lowerability floor → shadow diff budget → canary SLO burn), and the
promotion policy. It is deliberately a plain dataclass + JSON manifest
loader rather than a CRD client: the same document shape works as a
config-dir manifest today and as a CRD ``spec`` block when an apiserver
watch is wired (apis/v1alpha1.py holds the serving CRD conventions this
follows).

Manifest shape (docs/rollout.md "Declarative lifecycle"):

    {
      "kind": "PolicyRollout",
      "metadata": {"name": "tenant-a"},
      "spec": {
        "candidate": {"directory": "/etc/cedar/candidate"},
        "gates": {
          "lowerability_floor_pct": 95.0,
          "analyze": {"flip_budget": 0, "allowed_intents": []},
          "shadow": {"min_samples": 200, "diff_budget": 0},
          "canary": {"min_decisions": 50, "max_flips": 0},
          "slo": {"burn_ceiling": 2.0, "window_s": 300}
        },
        "promotion": {"mode": "auto", "canary_ladder": [10, 50, 100]},
        "stage_deadline_s": 300,
        "max_retries": 3
      }
    }

``candidate`` takes exactly one of ``directory`` / ``source`` (inline
policy text) / ``crd: true`` — the RolloutController staging sources —
or, programmatically only, ``tiers`` (a list of PolicySet, opaque to the
journal). An empty ``canary_ladder`` skips the canary stage entirely
(shadow evidence promotes directly) — the posture for webhook-server
deployments where no in-process canary router sits on the live path.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Tuple

# same DNS-label-ish shape the tenancy registry enforces (tenant ids
# become metric label values and journal keys)
_TENANT_RE = re.compile(r"^[a-z0-9]([a-z0-9._-]{0,62}[a-z0-9])?$", re.I)

_SOURCE_KEYS = ("directory", "source", "crd", "tiers")

PROMOTION_AUTO = "auto"
PROMOTION_MANUAL = "manual"


class SpecError(ValueError):
    """A PolicyRollout document failed validation."""


@dataclass(frozen=True)
class PolicyRolloutSpec:
    """One tenant's declarative rollout: candidate + gates + promotion."""

    tenant: str
    candidate: dict
    # gate tier 1: verify — blocking findings always halt; additionally
    # the fully-lowerable coverage percent must meet the floor
    lowerability_floor_pct: float = 0.0
    # gate tier 1.5 (opt-in via gates.analyze in the manifest): the
    # device-exact semantic diff between the live and candidate sets
    # (analysis/semdiff.py). Decision flips outside the allowed-intent
    # selectors beyond the flip budget breach BEFORE any live traffic
    # sees the candidate; an oracle disagreement always breaches.
    analyze_enabled: bool = False
    analyze_flip_budget: int = 0
    # each selector is a dict of optional keys: kind
    # ("allow_to_deny"/"deny_to_allow") matched exactly; principal/
    # action/resource globs matched against the exemplar's Type::id
    analyze_allowed_intents: Tuple[dict, ...] = ()
    analyze_universe_budget: int = 2048
    analyze_oracle_sample: int = 32
    # gate tier 2: shadow — evidence window and diff budget
    shadow_min_samples: int = 100
    shadow_diff_budget: int = 0
    # gate tier 3: canary — per-rung decision quorum, flip tolerance, and
    # the SLO availability-burn ceiling over the trailing window
    canary_min_decisions: int = 50
    canary_max_flips: int = 0
    slo_burn_ceiling: float = 2.0
    slo_burn_window_s: float = 300.0
    # promotion policy
    promotion: str = PROMOTION_AUTO
    canary_ladder: Tuple[int, ...] = (10, 50, 100)
    # per-stage resilience budget
    stage_deadline_s: float = 300.0
    max_retries: int = 3

    def __post_init__(self):
        if not _TENANT_RE.match(self.tenant or ""):
            raise SpecError(f"invalid tenant id {self.tenant!r}")
        keys = [k for k in _SOURCE_KEYS if self.candidate.get(k)]
        if len(keys) != 1:
            raise SpecError(
                "candidate must name exactly one of "
                f"{_SOURCE_KEYS} (got {sorted(self.candidate)})"
            )
        if self.promotion not in (PROMOTION_AUTO, PROMOTION_MANUAL):
            raise SpecError(
                f"promotion must be {PROMOTION_AUTO!r} or "
                f"{PROMOTION_MANUAL!r}, not {self.promotion!r}"
            )
        ladder = tuple(self.canary_ladder)
        if any(not (0 < p <= 100) for p in ladder):
            raise SpecError(f"canary_ladder percents must be in (0, 100]: {ladder}")
        if list(ladder) != sorted(ladder):
            raise SpecError(f"canary_ladder must ascend: {ladder}")
        object.__setattr__(self, "canary_ladder", ladder)
        for name in ("shadow_min_samples", "canary_min_decisions",
                     "max_retries", "analyze_flip_budget"):
            if getattr(self, name) < 0:
                raise SpecError(f"{name} must be >= 0")
        if self.analyze_universe_budget <= 0 or self.analyze_oracle_sample < 0:
            raise SpecError(
                "analyze universe_budget must be > 0 and oracle_sample >= 0"
            )
        intents = tuple(dict(s) for s in self.analyze_allowed_intents)
        for s in intents:
            bad = set(s) - {"kind", "principal", "action", "resource"}
            if bad:
                raise SpecError(
                    f"unknown allowed-intent selector keys: {sorted(bad)}"
                )
        object.__setattr__(self, "analyze_allowed_intents", intents)
        if self.stage_deadline_s <= 0:
            raise SpecError("stage_deadline_s must be > 0")

    def stage_kwargs(self) -> dict:
        """The RolloutController.stage(...) source kwargs."""
        c = self.candidate
        if c.get("tiers"):
            return {"tiers": c["tiers"]}
        if c.get("directory"):
            return {"directory": c["directory"]}
        if c.get("source"):
            return {"source": c["source"]}
        return {"crd": True}

    def to_dict(self) -> dict:
        """Manifest-shaped dict (journal + /debug/lifecycle). An opaque
        ``tiers`` candidate serializes as a marker — resume() needs the
        caller to re-supply such specs."""
        cand = dict(self.candidate)
        if cand.get("tiers"):
            cand["tiers"] = f"<opaque:{len(cand['tiers'])} tier(s)>"
        return {
            "kind": "PolicyRollout",
            "metadata": {"name": self.tenant},
            "spec": {
                "candidate": cand,
                "gates": {
                    "lowerability_floor_pct": self.lowerability_floor_pct,
                    **(
                        {
                            "analyze": {
                                "flip_budget": self.analyze_flip_budget,
                                "allowed_intents": [
                                    dict(s)
                                    for s in self.analyze_allowed_intents
                                ],
                                "universe_budget": self.analyze_universe_budget,
                                "oracle_sample": self.analyze_oracle_sample,
                            }
                        }
                        if self.analyze_enabled
                        else {}
                    ),
                    "shadow": {
                        "min_samples": self.shadow_min_samples,
                        "diff_budget": self.shadow_diff_budget,
                    },
                    "canary": {
                        "min_decisions": self.canary_min_decisions,
                        "max_flips": self.canary_max_flips,
                    },
                    "slo": {
                        "burn_ceiling": self.slo_burn_ceiling,
                        "window_s": self.slo_burn_window_s,
                    },
                },
                "promotion": {
                    "mode": self.promotion,
                    "canary_ladder": list(self.canary_ladder),
                },
                "stage_deadline_s": self.stage_deadline_s,
                "max_retries": self.max_retries,
            },
        }


def spec_from_dict(doc: dict) -> PolicyRolloutSpec:
    """Parse + validate one PolicyRollout manifest document."""
    if not isinstance(doc, dict):
        raise SpecError("PolicyRollout must be a JSON object")
    kind = doc.get("kind", "PolicyRollout")
    if kind != "PolicyRollout":
        raise SpecError(f"kind must be PolicyRollout, not {kind!r}")
    tenant = ((doc.get("metadata") or {}).get("name")) or doc.get("tenant")
    if not tenant:
        raise SpecError("metadata.name (the tenant id) is required")
    spec = doc.get("spec") or {}
    if not isinstance(spec, dict):
        raise SpecError("spec must be an object")
    gates = spec.get("gates") or {}
    shadow = gates.get("shadow") or {}
    canary = gates.get("canary") or {}
    slo = gates.get("slo") or {}
    analyze = gates.get("analyze")
    promotion = spec.get("promotion") or {}
    try:
        return PolicyRolloutSpec(
            tenant=tenant,
            candidate=dict(spec.get("candidate") or {}),
            lowerability_floor_pct=float(
                gates.get("lowerability_floor_pct", 0.0)
            ),
            analyze_enabled=analyze is not None,
            analyze_flip_budget=int((analyze or {}).get("flip_budget", 0)),
            analyze_allowed_intents=tuple(
                (analyze or {}).get("allowed_intents", ())
            ),
            analyze_universe_budget=int(
                (analyze or {}).get("universe_budget", 2048)
            ),
            analyze_oracle_sample=int(
                (analyze or {}).get("oracle_sample", 32)
            ),
            shadow_min_samples=int(shadow.get("min_samples", 100)),
            shadow_diff_budget=int(shadow.get("diff_budget", 0)),
            canary_min_decisions=int(canary.get("min_decisions", 50)),
            canary_max_flips=int(canary.get("max_flips", 0)),
            slo_burn_ceiling=float(slo.get("burn_ceiling", 2.0)),
            slo_burn_window_s=float(slo.get("window_s", 300.0)),
            promotion=promotion.get("mode", PROMOTION_AUTO),
            canary_ladder=tuple(
                promotion.get("canary_ladder", (10, 50, 100))
            ),
            stage_deadline_s=float(spec.get("stage_deadline_s", 300.0)),
            max_retries=int(spec.get("max_retries", 3)),
        )
    except (TypeError, ValueError) as e:
        if isinstance(e, SpecError):
            raise
        raise SpecError(f"malformed PolicyRollout for {tenant!r}: {e}")


def load_spec_file(path: str) -> PolicyRolloutSpec:
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise SpecError(f"{path}: not valid JSON: {e}") from None
    try:
        return spec_from_dict(doc)
    except SpecError as e:
        raise SpecError(f"{path}: {e}") from None


def load_specs_dir(directory: str) -> list:
    """Every ``*.json`` PolicyRollout in the directory, sorted by
    filename; duplicate tenants are an error (two manifests driving one
    tenant's rollout would fight)."""
    specs = []
    seen = {}
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json") or name.startswith("."):
            continue
        path = os.path.join(directory, name)
        spec = load_spec_file(path)
        if spec.tenant in seen:
            raise SpecError(
                f"{path}: duplicate PolicyRollout for tenant "
                f"{spec.tenant!r} (also in {seen[spec.tenant]})"
            )
        seen[spec.tenant] = path
        specs.append(spec)
    return specs


__all__ = [
    "PolicyRolloutSpec",
    "SpecError",
    "PROMOTION_AUTO",
    "PROMOTION_MANUAL",
    "spec_from_dict",
    "load_spec_file",
    "load_specs_dir",
]
