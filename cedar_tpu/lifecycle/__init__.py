"""cedar_tpu.lifecycle — the declarative policy-lifecycle controller.

author → verify → shadow → canary → promote as a self-driving,
self-healing control loop: a per-tenant ``PolicyRollout`` spec (spec.py)
names a candidate source, ordered evidence gates, and a promotion
policy; the controller (controller.py) drives the existing rollout /
analysis / SLO primitives through a driver binding (driver.py), journals
every transition (journal.py) for crash resume, and halts + rolls back
automatically on any gate breach. docs/rollout.md "Declarative
lifecycle" is the operator guide; ``bench.py --lifecycle`` is the
storm-backed acceptance harness.
"""

from .controller import (
    STAGE_ANALYZING,
    STAGE_CANARY,
    STAGE_CODES,
    STAGE_FAILED,
    STAGE_HALTED,
    STAGE_PENDING,
    STAGE_PROMOTED,
    STAGE_PROMOTING,
    STAGE_ROLLED_BACK,
    STAGE_SHADOWING,
    STAGE_VERIFYING,
    LifecycleController,
    LifecycleError,
)
from .driver import DriverError, GateBreach, RolloutLifecycleDriver
from .journal import TERMINAL_STAGES, LifecycleJournal
from .spec import (
    PROMOTION_AUTO,
    PROMOTION_MANUAL,
    PolicyRolloutSpec,
    SpecError,
    load_spec_file,
    load_specs_dir,
    spec_from_dict,
)

__all__ = [
    "LifecycleController",
    "LifecycleError",
    "LifecycleJournal",
    "RolloutLifecycleDriver",
    "DriverError",
    "GateBreach",
    "PolicyRolloutSpec",
    "SpecError",
    "spec_from_dict",
    "load_spec_file",
    "load_specs_dir",
    "PROMOTION_AUTO",
    "PROMOTION_MANUAL",
    "TERMINAL_STAGES",
    "STAGE_CODES",
    "STAGE_PENDING",
    "STAGE_VERIFYING",
    "STAGE_ANALYZING",
    "STAGE_SHADOWING",
    "STAGE_CANARY",
    "STAGE_PROMOTING",
    "STAGE_PROMOTED",
    "STAGE_HALTED",
    "STAGE_ROLLED_BACK",
    "STAGE_FAILED",
]
