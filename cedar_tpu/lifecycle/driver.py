"""The lifecycle controller's hands: one tenant's serving-stack binding.

The controller (controller.py) is a pure evidence-driven state machine —
it never touches an engine. Everything stateful it needs doing goes
through a driver:

  * ``verify``        — analyze the candidate tiers (permissive mode) and
                        return lowerability-coverage evidence;
  * ``start_shadow``  — stage the candidate on the tenant's
                        RolloutController (strict analysis gate, candidate
                        engines, shadow evaluator);
  * ``shadow_evidence`` — the DiffReport rollup (samples + diffs);
  * ``set_canary``    — move the canary traffic split to a ladder rung;
  * ``canary_evidence`` — canary decisions, avoided flips, and the SLO
                        availability burn over the gate window;
  * ``promote`` / ``rollback`` / ``reset`` — the terminal actions.

Transient failures raise ``DriverError`` (the controller retries them
with decorrelated-jitter backoff under the stage deadline); permanent
gate rejections raise ``GateBreach`` (the controller halts + rolls back).

The canary split lives here too: ``serve()`` is the tenant's live
authorize path in embedded deployments (bench --lifecycle, tests). A
deterministic per-body hash routes ``fraction`` of traffic through the
candidate stack; the candidate's answer serves ONLY when its decision
agrees with the live engine's — a disagreeing canary answer is served
from the LIVE engine and counted as an avoided flip (fail-safe canary:
the rung proves the candidate plane's operational health, while decision
deltas are the shadow gate's evidence, and a flip that shadow missed
halts the rollout via ``canary_max_flips``). Candidate latency/errors
land in the SLO tracker under ``canary:<tenant>``, which is what the
burn-rate gate reads. The ``lifecycle.canary`` chaos seam fires per
canary-slice evaluation — an injected error burns the canary SLO without
touching live answers (the lifecycle-breach game day).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from typing import Callable, Optional, Tuple

from ..chaos.registry import chaos_fire

log = logging.getLogger(__name__)


class DriverError(RuntimeError):
    """A transient stage failure — retry under the stage budget."""


class GateBreach(RuntimeError):
    """A permanent gate rejection — halt and roll back."""

    def __init__(self, gate: str, evidence: Optional[dict] = None):
        super().__init__(f"gate breach: {gate}")
        self.gate = gate
        self.evidence = evidence or {}


class RolloutLifecycleDriver:
    """Binds one tenant's lifecycle to a RolloutController + SLOTracker
    (+ an optional live-eval callable for the embedded canary router)."""

    def __init__(
        self,
        tenant: str,
        rollout,
        slo=None,
        live_eval: Optional[Callable[[bytes], Tuple[str, str]]] = None,
        warm: str = "off",
        promote_force: bool = False,
        sample_rate: float = 1.0,
        clock: Callable[[], float] = time.perf_counter,
        live_tiers: Optional[Callable[[], list]] = None,
    ):
        self.tenant = tenant
        self.rollout = rollout
        self.slo = slo
        self.live_eval = live_eval
        # provider of the LIVE tier PolicySets, required only when the
        # spec enables the analyze gate (the semantic diff needs both
        # sides; the rollout controller only knows the candidate)
        self.live_tiers = live_tiers
        self.warm = warm
        self.promote_force = promote_force
        self.sample_rate = sample_rate
        self._clock = clock
        self.slo_path = f"canary:{tenant}"
        self.canary_fraction = 0.0
        self._counter_lock = threading.Lock()
        self._canary_decisions = 0
        self._canary_flips = 0

    # --------------------------------------------------- controller side

    def _resolve_tiers(self, spec) -> list:
        """The candidate tiers, from whichever source the spec names —
        the same resolution stage() performs, run early so verify() can
        gate on analysis evidence before anything compiles."""
        c = spec.candidate
        if c.get("tiers"):
            return list(c["tiers"])
        from ..rollout.source import (
            candidate_tiers_from_directory,
            candidate_tiers_from_objects,
            candidate_tiers_from_source,
        )

        if c.get("directory"):
            return candidate_tiers_from_directory(c["directory"])
        if c.get("source"):
            return candidate_tiers_from_source(c["source"])
        provider = getattr(self.rollout, "_crd_candidate_provider", None)
        if provider is None:
            raise DriverError(
                "verify: candidate names crd=true but no CRD candidate "
                "provider is wired on the rollout controller"
            )
        return candidate_tiers_from_objects(provider())

    def verify(self, spec) -> dict:
        """Tier-1 evidence: permissive-mode analysis of the candidate —
        blocking-finding count and fully-lowerable coverage percent."""
        from ..analysis.loadgate import enforce

        try:
            tiers = self._resolve_tiers(spec)
            _, report = enforce(tiers, "permissive", publish=False)
        except Exception as e:  # noqa: BLE001 — source/analysis hiccups retry
            raise DriverError(f"verify: {e}") from e
        cov = report.coverage or {}
        return {
            "policies": cov.get("policies", 0),
            "lowerable_pct": float(cov.get("lowerable_pct", 0.0)),
            "blocking": len(report.blocking()),
        }

    def analyze(self, spec) -> dict:
        """Tier-1.5 evidence (opt-in): the device-exact semantic diff
        between the live and candidate tiers (analysis/semdiff.py), run
        entirely host-side BEFORE any live traffic touches the
        candidate. Returns flip counts split by allowed-intent coverage
        plus the interpreter-oracle cross-check; the controller breaches
        on out-of-intent flips over the budget or any disagreement."""
        from ..analysis.semdiff import semantic_diff

        if self.live_tiers is None:
            raise DriverError(
                "analyze: spec enables the analyze gate but no live_tiers "
                "provider is wired on the driver"
            )
        try:
            live = list(self.live_tiers())
            cand = self._resolve_tiers(spec)
            diff = semantic_diff(
                live,
                cand,
                budget=spec.analyze_universe_budget,
                oracle_sample=spec.analyze_oracle_sample,
            )
        except DriverError:
            raise
        except Exception as e:  # noqa: BLE001 — compile/source hiccups retry
            raise DriverError(f"analyze: {e}") from e
        out_of_intent = diff.out_of_intent(spec.analyze_allowed_intents)
        try:
            from ..server.metrics import record_semdiff_flips

            for kind, n in diff.flip_counts.items():
                record_semdiff_flips(self.tenant, kind, n)
        except Exception:  # noqa: BLE001 — metrics never gate the machine
            pass
        return {
            "requests": diff.n_requests,
            "exhaustive": diff.exact,
            "flips": dict(diff.flip_counts),
            "total_flips": diff.total_flips,
            "out_of_intent_flips": out_of_intent,
            "oracle_sampled": diff.oracle.get("sampled", 0),
            "oracle_disagreements": diff.oracle.get("disagreements", 0),
            # a few concrete flipped requests for the WAL/audit evidence
            "exemplars": diff.flips[:5],
            "seconds": round(diff.seconds, 3),
        }

    def start_shadow(self, spec) -> None:
        """Stage the candidate (strict analysis gate, candidate engines,
        shadow evaluator). An analysis rejection here is a lowerability
        breach — verify() already measured the same corpus, so reaching
        it means the floor passed but strict blocking findings exist."""
        from ..rollout.controller import RolloutError

        try:
            self.rollout.stage(
                description=f"lifecycle:{self.tenant}",
                warm=self.warm,
                sample_rate=self.sample_rate,
                **spec.stage_kwargs(),
            )
        except RolloutError as e:
            if "rejected by analysis" in str(e):
                raise GateBreach("lowerability", {"error": str(e)}) from e
            raise DriverError(f"stage: {e}") from e
        except Exception as e:  # noqa: BLE001 — compile/source hiccups retry
            raise DriverError(f"stage: {e}") from e

    def shadow_evidence(self) -> dict:
        report = self.rollout.report
        if report is None:
            raise DriverError("shadow evidence: no diff report (not staged)")
        return {
            "samples": report.total_evaluations,
            "diffs": report.total_diffs,
        }

    def set_canary(self, percent: float) -> None:
        """Move the canary split to a ladder rung. Decision counts reset
        per rung (each rung earns its own quorum); avoided-flip counts
        are cumulative — the candidate didn't change between rungs."""
        self.canary_fraction = max(0.0, min(1.0, percent / 100.0))
        with self._counter_lock:
            self._canary_decisions = 0

    def canary_evidence(self, window_s: float) -> dict:
        with self._counter_lock:
            decisions = self._canary_decisions
            flips = self._canary_flips
        burn = 0.0
        if self.slo is not None:
            burn = self.slo.availability_burn(self.slo_path, window_s)
        return {"decisions": decisions, "flips": flips, "burn": burn}

    def promote(self) -> None:
        from ..rollout.controller import RolloutError

        try:
            self.rollout.promote(force=self.promote_force)
        except RolloutError as e:
            # warm-up still running, concurrent stage, … — all retryable
            raise DriverError(f"promote: {e}") from e
        self.canary_fraction = 0.0

    def rollback(self) -> None:
        from ..rollout.controller import RolloutError

        self.canary_fraction = 0.0
        if self.rollout.status().get("state") == "idle":
            return  # nothing staged or promoted: rollback is a no-op
        try:
            self.rollout.rollback()
        except RolloutError as e:
            err = DriverError(f"rollback: {e}")
            err.detail = getattr(e, "detail", None)
            raise err from e

    def reset(self) -> None:
        """Crash-resume cleanup: whatever the dead controller left staged
        or promoted is unwound so the machine can restart from a clean
        live-only serving plane (no mixed-generation window: the live
        engines serve exactly one lineage after this returns)."""
        self.canary_fraction = 0.0
        with self._counter_lock:
            self._canary_decisions = 0
            self._canary_flips = 0
        state = self.rollout.status().get("state")
        if state in ("staged", "promoted"):
            self.rollback()

    # ------------------------------------------------------ serving side

    def serve(self, body: bytes, endpoint: str = "authorize"):
        """The tenant's live authorize path in embedded deployments:
        evaluate live, then either feed the shadow evaluator or run the
        canary slice. Returns the served (decision, reason)."""
        if self.live_eval is None:
            raise DriverError("serve: no live_eval wired")
        live = self.live_eval(body)
        fraction = self.canary_fraction
        if fraction > 0.0 and self._in_canary_slice(body, fraction):
            return self._canary_eval(body, live)
        # not canary traffic: offer to the shadow evaluator (no-op with
        # nothing staged; never raises, never blocks)
        self.rollout.offer(endpoint, body, live)
        return live

    @staticmethod
    def _in_canary_slice(body: bytes, fraction: float) -> bool:
        # stable per-body hash: the same request always lands on the same
        # side of the split, and a rung increase only ADDS bodies to the
        # slice (crc in [0,1) compared against the growing fraction)
        return (zlib.crc32(body) % 10000) / 10000.0 < fraction

    def _canary_eval(self, body: bytes, live):
        t0 = self._clock()
        error = False
        served = live
        try:
            chaos_fire(
                "lifecycle.canary", payload={"tenant": self.tenant}
            )
            cand = self._candidate_answer(body)
            if cand is not None:
                if cand[0] != live[0]:
                    # fail-safe: the disagreeing answer does NOT serve
                    with self._counter_lock:
                        self._canary_flips += 1
                else:
                    served = cand
        except Exception:  # noqa: BLE001 — chaos + candidate failures burn SLO
            error = True
        with self._counter_lock:
            self._canary_decisions += 1
        if self.slo is not None:
            try:
                self.slo.record(self.slo_path, self._clock() - t0, error)
            except Exception:  # noqa: BLE001 — SLO must never hurt serving
                log.exception("canary SLO record failed")
        return served

    def _candidate_answer(self, body: bytes):
        stack = self.rollout.candidate_stack()
        if stack is None:
            return None
        authorizer, _admission = stack
        if authorizer is None:
            return None
        from ..server.http import get_authorizer_attributes

        attributes = get_authorizer_attributes(json.loads(body))
        return authorizer.authorize_batch([attributes])[0]


__all__ = ["DriverError", "GateBreach", "RolloutLifecycleDriver"]
