"""Write-ahead journal for lifecycle transitions.

Every stage transition is journaled BEFORE the controller's in-memory
state (or any metric/audit record) changes: a controller that dies
between the append and the mutation resumes from a journal that is at
most one transition AHEAD of what it acted on, never behind — replaying
such a record re-enters a stage the driver can safely restart
(controller.py resume()). Appends are flushed per record (JSONL, one
object per line); a torn final line from a mid-write crash is dropped at
replay with a warning rather than poisoning the whole history.

The ``lifecycle.journal`` chaos seam fires at the top of every append:
a ``kill`` rule is the controller-crash drill (the ThreadKilled unwinds
tick() before the record lands), an ``error`` rule is a journal-write
failure (the transition retries under the stage's backoff budget).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

from ..chaos.registry import chaos_fire

log = logging.getLogger(__name__)

# stages a rollout can never leave (controller.py owns the machine; the
# journal needs the set to answer replay() without importing it)
TERMINAL_STAGES = frozenset({"promoted", "rolled_back", "failed"})


class LifecycleJournal:
    """Append-only JSONL transition log, file-backed (``path``) or
    in-memory (tests, ephemeral benches). Thread-safe; appends are
    flushed + fsync'd so a crash loses at most the in-flight record."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._mem: List[dict] = []
        self._seq = 0
        self._fh = None
        if path is not None:
            # recover the sequence counter from an existing journal so a
            # resumed controller keeps appending monotonically
            for rec in self._read_file():
                self._seq = max(self._seq, int(rec.get("seq", 0)))
            self._fh = open(path, "a")
            # heal a torn tail: a mid-write crash can leave a final line
            # with no newline; appending onto it would corrupt the NEXT
            # record too, so terminate it first (replay drops the torn
            # line either way)
            if os.path.getsize(path) > 0:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        self._fh.write("\n")
                        self._fh.flush()

    def append(self, record: dict) -> dict:
        """Durably append one transition record (adds ``seq``); returns
        the record as written. Raises on write failure — the caller's
        transition has NOT happened until this returns."""
        chaos_fire("lifecycle.journal", payload=record)
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, **record}
            if self._fh is not None:
                self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
                self._fh.flush()
                os.fsync(self._fh.fileno())
            else:
                self._mem.append(rec)
        return rec

    def records(self) -> List[dict]:
        """Every journal record, in append order."""
        if self.path is None:
            with self._lock:
                return list(self._mem)
        with self._lock:
            return self._read_file()

    def _read_file(self) -> List[dict]:
        if self.path is None or not os.path.exists(self.path):
            return []
        out: List[dict] = []
        with open(self.path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # torn tail from a mid-write crash: only acceptable on
                    # the final line; anything earlier is corruption worth
                    # shouting about either way
                    log.warning(
                        "lifecycle journal %s: dropping unparseable "
                        "line %d", self.path, i + 1,
                    )
        return out

    def replay(self) -> Dict[str, dict]:
        """Per-tenant resume view: the last ``applied`` spec document and
        the last recorded stage. Tenants whose last lifecycle record is a
        ``deleted`` event are omitted (their rollout no longer exists)."""
        state: Dict[str, dict] = {}
        for rec in self.records():
            tenant = rec.get("tenant")
            if not tenant:
                continue
            event = rec.get("event")
            if event == "deleted":
                state.pop(tenant, None)
                continue
            entry = state.setdefault(
                tenant, {"stage": "pending", "spec": None, "last": None}
            )
            if event == "applied":
                entry["spec"] = rec.get("spec")
                entry["stage"] = "pending"
            elif rec.get("to"):
                entry["stage"] = rec["to"]
            entry["last"] = rec
        return state

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


__all__ = ["LifecycleJournal", "TERMINAL_STAGES"]
