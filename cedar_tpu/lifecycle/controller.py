"""The declarative lifecycle controller: a self-driving, self-healing
author → verify → shadow → canary → promote loop.

Every rollout primitive in the repo is evidence-producing but
operator-driven; this controller closes the loop. Each tenant's
``PolicyRolloutSpec`` (spec.py) compiles into a per-tenant state machine:

    pending → verifying → [analyzing] → shadowing → canary (ladder rungs)
            → promoting → promoted
    any gate breach → halted → rolled_back       (automatic)
    rollback failure / retry exhaustion → failed

Stages advance ONLY on recorded evidence — the analysis report's
lowerability coverage, the shadow DiffReport's sample/diff counts, the
canary slice's SLO availability burn — and every transition is
write-ahead journaled (journal.py), audited, and exported
(cedar_lifecycle_stage{tenant} + transition counters; /debug/lifecycle
renders ``status()``).

Self-healing: transient stage failures (DriverError, injected
ChaosError) retry with decorrelated-jitter backoff (server/backoff.py)
as a NON-BLOCKING per-tenant retry-at timestamp — one tenant's flapping
stage never delays a neighbor's tick — bounded by the spec's
``max_retries`` and per-stage deadline; exhaustion is a ``deadline`` /
``retry_exhausted`` breach like any other, so the machine halts and
rolls back instead of wedging. A controller crash (the
``lifecycle.journal`` kill drill) resumes via ``resume()``: terminal
stages stay terminal, anything in flight has its driver unwound to the
live-only serving plane (no mixed-generation window) and restarts from
``pending`` to re-earn promotion from fresh evidence.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from ..chaos.registry import ChaosError, chaos_fire
from ..server.backoff import Backoff
from .driver import DriverError, GateBreach
from .journal import TERMINAL_STAGES, LifecycleJournal
from .spec import PROMOTION_MANUAL, PolicyRolloutSpec, spec_from_dict

log = logging.getLogger(__name__)

STAGE_PENDING = "pending"
STAGE_VERIFYING = "verifying"
STAGE_ANALYZING = "analyzing"
STAGE_SHADOWING = "shadowing"
STAGE_CANARY = "canary"
STAGE_PROMOTING = "promoting"
STAGE_PROMOTED = "promoted"
STAGE_HALTED = "halted"
STAGE_ROLLED_BACK = "rolled_back"
STAGE_FAILED = "failed"

# gauge codes (cedar_lifecycle_stage help text mirrors this table)
STAGE_CODES = {
    STAGE_PENDING: 0,
    STAGE_VERIFYING: 1,
    STAGE_SHADOWING: 2,
    STAGE_CANARY: 3,
    STAGE_PROMOTING: 4,
    STAGE_PROMOTED: 5,
    STAGE_HALTED: 6,
    STAGE_ROLLED_BACK: 7,
    STAGE_FAILED: 8,
    # appended (not renumbered) so dashboards keyed on 0-8 stay valid:
    # the opt-in semantic-diff gate between verifying and shadowing
    STAGE_ANALYZING: 9,
}


class LifecycleError(RuntimeError):
    """A controller-level operation was invalid (unknown tenant,
    conflicting apply, …)."""


class _TenantRollout:
    """One tenant's in-flight rollout: spec + driver + machine state."""

    def __init__(self, spec: PolicyRolloutSpec, driver, backoff: Backoff,
                 now: float):
        self.spec = spec
        self.driver = driver
        self.stage = STAGE_PENDING
        self.stage_entered = now
        self.backoff = backoff
        self.attempts = 0
        self.next_retry_at = 0.0
        self.rung = -1  # index into spec.canary_ladder; -1 = not started
        self.approved = False
        self.awaiting_approval = False
        self.evidence: dict = {}
        self.halt: Optional[dict] = None
        self.error: Optional[str] = None


class LifecycleController:
    """Owns every tenant's rollout machine; ``tick()`` advances them all
    (each at most one step), isolating tenants from one another."""

    def __init__(
        self,
        journal: Optional[LifecycleJournal] = None,
        audit_log=None,
        clock: Callable[[], float] = time.monotonic,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        backoff_uniform=None,
    ):
        self.journal = journal or LifecycleJournal()
        self.audit_log = audit_log
        self._clock = clock
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._backoff_uniform = backoff_uniform
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantRollout] = {}
        self._loop: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -------------------------------------------------------- spec admin

    def _new_backoff(self) -> Backoff:
        kwargs = {}
        if self._backoff_uniform is not None:
            kwargs["uniform"] = self._backoff_uniform
        return Backoff(self._backoff_base_s, self._backoff_cap_s, **kwargs)

    def apply(self, spec: PolicyRolloutSpec, driver) -> dict:
        """Admit one tenant's rollout. Re-applying over a TERMINAL
        machine restarts it (a new journal epoch for the tenant);
        re-applying over an in-flight one is refused — halt it first
        (delete) or let it finish."""
        with self._lock:
            existing = self._tenants.get(spec.tenant)
            if existing is not None and existing.stage not in TERMINAL_STAGES:
                raise LifecycleError(
                    f"apply: tenant {spec.tenant!r} already has a rollout "
                    f"in flight (stage {existing.stage}); delete it first"
                )
            m = _TenantRollout(
                spec, driver, self._new_backoff(), self._clock()
            )
            self._tenants[spec.tenant] = m
        self.journal.append(
            {"event": "applied", "tenant": spec.tenant,
             "spec": spec.to_dict()}
        )
        self._publish_stage(spec.tenant, STAGE_PENDING)
        self._audit(spec.tenant, "applied", stage=STAGE_PENDING)
        return {"tenant": spec.tenant, "stage": STAGE_PENDING}

    def delete(self, tenant: str) -> None:
        """Remove a tenant's rollout spec: unwind anything in flight,
        drop the stage gauge row, free the tenant's metric label slot."""
        with self._lock:
            m = self._tenants.pop(tenant, None)
        if m is None:
            raise LifecycleError(f"delete: no rollout for tenant {tenant!r}")
        if m.stage not in TERMINAL_STAGES:
            try:
                m.driver.reset()
            except Exception:  # noqa: BLE001 — deletion must complete
                log.exception(
                    "lifecycle delete(%s): driver reset failed", tenant
                )
        self.journal.append({"event": "deleted", "tenant": tenant})
        self._audit(tenant, "deleted", stage=m.stage)
        try:
            from ..server.metrics import clear_lifecycle_tenant

            clear_lifecycle_tenant(tenant)
        except Exception:  # noqa: BLE001 — metrics never gate admin
            pass

    def approve(self, tenant: str) -> dict:
        """Manual-promotion consent; the next tick promotes (a rollout
        holding at the last canary rung keeps gating burn/flips until
        then)."""
        with self._lock:
            m = self._tenants.get(tenant)
            if m is None:
                raise LifecycleError(
                    f"approve: no rollout for tenant {tenant!r}"
                )
            m.approved = True
        self.journal.append({"event": "approved", "tenant": tenant})
        self._audit(tenant, "approved", stage=m.stage)
        return {"tenant": tenant, "stage": m.stage, "approved": True}

    # ------------------------------------------------------- the machine

    def tick(self) -> Dict[str, str]:
        """Advance every tenant's machine at most one step. Per-tenant
        containment: an unexpected exception in one machine becomes that
        machine's transient failure, never a neighbor's problem. Chaos
        ``kill`` rules (ThreadKilled, a BaseException) DO propagate —
        that is the controller-crash drill."""
        with self._lock:
            machines = list(self._tenants.values())
        out: Dict[str, str] = {}
        for m in machines:
            try:
                self._advance(m)
            except Exception as e:  # noqa: BLE001 — tenant isolation
                log.exception(
                    "lifecycle tick(%s) raised; treating as transient",
                    m.spec.tenant,
                )
                try:
                    self._note_transient(m, e)
                except Exception:  # noqa: BLE001 — isolation, always
                    log.exception(
                        "lifecycle tick(%s) containment failed",
                        m.spec.tenant,
                    )
            out[m.spec.tenant] = m.stage
        return out

    def _advance(self, m: _TenantRollout) -> None:
        if m.stage in TERMINAL_STAGES:
            return
        now = self._clock()
        if now < m.next_retry_at:
            return
        try:
            self._advance_stage(m, now)
        except GateBreach as b:
            self._breach(m, b.gate, b.evidence)
        except (DriverError, ChaosError) as e:
            self._note_transient(m, e)

    def _advance_stage(self, m: _TenantRollout, now: float) -> None:
        spec = m.spec
        tenant = spec.tenant
        if m.stage == STAGE_PENDING:
            self._transition(m, STAGE_VERIFYING)
            return

        if m.stage == STAGE_VERIFYING:
            chaos_fire(
                "lifecycle.gate",
                payload={"tenant": tenant, "stage": m.stage},
            )
            ev = m.driver.verify(spec)
            m.evidence["verify"] = ev
            if ev.get("blocking", 0) > 0 or (
                ev.get("lowerable_pct", 0.0) < spec.lowerability_floor_pct
            ):
                raise GateBreach("lowerability", ev)
            if spec.analyze_enabled:
                # opt-in semantic-diff gate runs BEFORE any live traffic
                # (shadow mirroring included) sees the candidate
                self._transition(m, STAGE_ANALYZING, evidence=ev)
                return
            m.driver.start_shadow(spec)
            self._transition(m, STAGE_SHADOWING, evidence=ev)
            return

        if m.stage == STAGE_ANALYZING:
            chaos_fire(
                "lifecycle.gate",
                payload={"tenant": tenant, "stage": m.stage},
            )
            ev = m.driver.analyze(spec)
            m.evidence["analyze"] = ev
            if ev.get("oracle_disagreements", 0) > 0:
                # the plane and the interpreter disagreed on a sampled
                # request: a compiler bug, never promotable evidence
                raise GateBreach("analyze_oracle", ev)
            if ev.get("out_of_intent_flips", 0) > spec.analyze_flip_budget:
                raise GateBreach("semantic_diff", ev)
            m.driver.start_shadow(spec)
            self._transition(m, STAGE_SHADOWING, evidence=ev)
            return

        if m.stage == STAGE_SHADOWING:
            chaos_fire(
                "lifecycle.gate",
                payload={"tenant": tenant, "stage": m.stage},
            )
            ev = m.driver.shadow_evidence()
            m.evidence["shadow"] = ev
            if ev["samples"] >= spec.shadow_min_samples:
                if ev["diffs"] > spec.shadow_diff_budget:
                    raise GateBreach("shadow_diff", ev)
                if spec.canary_ladder:
                    m.rung = 0
                    m.driver.set_canary(spec.canary_ladder[0])
                    self._transition(
                        m, STAGE_CANARY, evidence=ev,
                        rung=0, percent=spec.canary_ladder[0],
                    )
                else:
                    # no canary rungs configured: shadow evidence is the
                    # final gate (webhook-server posture, spec.py)
                    self._enter_promotion(m, ev)
            elif now - m.stage_entered >= spec.stage_deadline_s:
                raise GateBreach("deadline", ev)
            return

        if m.stage == STAGE_CANARY:
            chaos_fire(
                "lifecycle.gate",
                payload={"tenant": tenant, "stage": m.stage},
            )
            ev = m.driver.canary_evidence(spec.slo_burn_window_s)
            m.evidence["canary"] = ev
            if ev["burn"] > spec.slo_burn_ceiling:
                raise GateBreach("slo_burn", ev)
            if ev["flips"] > spec.canary_max_flips:
                raise GateBreach("canary_flip", ev)
            if ev["decisions"] < spec.canary_min_decisions:
                if (
                    not m.awaiting_approval
                    and now - m.stage_entered >= spec.stage_deadline_s
                ):
                    raise GateBreach("deadline", ev)
                return
            if m.rung + 1 < len(spec.canary_ladder):
                m.rung += 1
                m.driver.set_canary(spec.canary_ladder[m.rung])
                # canary → canary: each rung re-earns its quorum under a
                # fresh per-stage deadline
                self._transition(
                    m, STAGE_CANARY, evidence=ev,
                    rung=m.rung, percent=spec.canary_ladder[m.rung],
                )
            else:
                self._enter_promotion(m, ev)
            return

        if m.stage == STAGE_PROMOTING:
            chaos_fire(
                "lifecycle.gate",
                payload={"tenant": tenant, "stage": m.stage},
            )
            m.driver.promote()
            self._transition(m, STAGE_PROMOTED)
            return

        if m.stage == STAGE_HALTED:
            # automatic rollback; its own retry budget started at the
            # halted transition
            try:
                m.driver.rollback()
            except DriverError as e:
                detail = getattr(e, "detail", None)
                if detail is not None:
                    # lineage divergence is permanent — retrying cannot
                    # un-diverge the serving plane
                    self._transition(
                        m, STAGE_FAILED, reason=str(e), detail=detail
                    )
                    return
                raise
            self._transition(m, STAGE_ROLLED_BACK, halt=m.halt)
            return

    def _enter_promotion(self, m: _TenantRollout, evidence: dict) -> None:
        if m.spec.promotion == PROMOTION_MANUAL and not m.approved:
            if not m.awaiting_approval:
                m.awaiting_approval = True
                self.journal.append(
                    {"event": "awaiting_approval",
                     "tenant": m.spec.tenant, "evidence": evidence}
                )
                self._audit(
                    m.spec.tenant, "awaiting_approval", stage=m.stage
                )
            return
        m.awaiting_approval = False
        self._transition(m, STAGE_PROMOTING, evidence=evidence)

    # ------------------------------------------------- breach + retries

    def _breach(self, m: _TenantRollout, gate: str, evidence: dict) -> None:
        tenant = m.spec.tenant
        try:
            from ..server.metrics import record_lifecycle_gate_breach

            record_lifecycle_gate_breach(tenant, gate)
        except Exception:  # noqa: BLE001 — metrics never gate the machine
            pass
        if m.stage == STAGE_HALTED:
            # the automatic rollback itself exhausted its budget
            self._transition(m, STAGE_FAILED, gate=gate, evidence=evidence)
            return
        m.halt = {"gate": gate, "stage": m.stage, "evidence": evidence}
        self._transition(m, STAGE_HALTED, gate=gate, evidence=evidence)

    def _note_transient(self, m: _TenantRollout, e: BaseException) -> None:
        if m.stage in TERMINAL_STAGES:
            return
        m.attempts += 1
        m.error = str(e)
        try:
            from ..server.metrics import record_lifecycle_retry

            record_lifecycle_retry(m.spec.tenant, m.stage)
        except Exception:  # noqa: BLE001
            pass
        now = self._clock()
        deadline = m.stage_entered + m.spec.stage_deadline_s
        if m.attempts > m.spec.max_retries:
            self._breach(
                m, "retry_exhausted",
                {"error": str(e), "attempts": m.attempts},
            )
        elif now >= deadline:
            self._breach(
                m, "deadline", {"error": str(e), "attempts": m.attempts}
            )
        else:
            m.next_retry_at = now + m.backoff.next()

    def _transition(self, m: _TenantRollout, to: str, **fields) -> None:
        """Write-ahead journal, then mutate, then publish: a crash inside
        the append resumes from the PRE-transition stage; a crash after
        it resumes from ``to`` — both restart cleanly (resume())."""
        frm = m.stage
        tenant = m.spec.tenant
        self.journal.append(
            {"event": "transition", "tenant": tenant, "from": frm,
             "to": to, **fields}
        )
        m.stage = to
        m.stage_entered = self._clock()
        m.attempts = 0
        m.next_retry_at = 0.0
        m.backoff.reset()
        m.error = None
        try:
            from ..server.metrics import record_lifecycle_transition

            record_lifecycle_transition(tenant, frm, to)
        except Exception:  # noqa: BLE001
            pass
        self._publish_stage(tenant, to)
        self._audit(tenant, "transition", frm=frm, to=to, **fields)
        log.info("lifecycle %s: %s -> %s", tenant, frm, to)

    @staticmethod
    def _publish_stage(tenant: str, stage: str) -> None:
        try:
            from ..server.metrics import set_lifecycle_stage

            set_lifecycle_stage(tenant, STAGE_CODES[stage])
        except Exception:  # noqa: BLE001
            pass

    def _audit(self, tenant: str, event: str, **fields) -> None:
        if self.audit_log is None:
            return
        try:
            self.audit_log.record(
                {"kind": "lifecycle", "tenant": tenant, "event": event,
                 "ts": time.time(), **fields}
            )
        except Exception:  # noqa: BLE001 — audit never gates the machine
            log.exception("lifecycle audit record failed")

    # ----------------------------------------------------- crash resume

    def resume(self, drivers: dict, specs: Optional[dict] = None) -> dict:
        """Rebuild the per-tenant machines from the journal after a
        controller crash. ``drivers`` maps tenant → driver bound to the
        (surviving or rebuilt) serving stack; ``specs`` optionally
        overrides the journaled spec documents (REQUIRED for candidates
        staged from opaque in-memory tiers, which don't journal).

        Terminal stages stay terminal. Anything in flight — including a
        crash mid-canary — has its driver unwound to the live-only plane
        (canary split zeroed, staged candidate discarded, un-finalized
        promotion restored) and restarts from ``pending``: the machine
        re-earns promotion from fresh evidence, which trivially
        guarantees no mixed-generation serving window survives the
        crash."""
        out = {}
        for tenant, entry in self.journal.replay().items():
            driver = drivers.get(tenant)
            if driver is None:
                log.warning(
                    "lifecycle resume: no driver for journaled tenant "
                    "%s; skipping", tenant,
                )
                continue
            spec = (specs or {}).get(tenant)
            if spec is None:
                if not entry.get("spec"):
                    log.warning(
                        "lifecycle resume: no spec for tenant %s", tenant
                    )
                    continue
                spec = spec_from_dict(entry["spec"])
            m = _TenantRollout(spec, driver, self._new_backoff(),
                               self._clock())
            stage = entry["stage"]
            if stage in TERMINAL_STAGES:
                m.stage = stage
            else:
                try:
                    driver.reset()
                except Exception as e:  # noqa: BLE001 — must not wedge resume
                    log.exception(
                        "lifecycle resume(%s): driver reset failed", tenant
                    )
                    m.stage = STAGE_FAILED
                    self.journal.append(
                        {"event": "transition", "tenant": tenant,
                         "from": stage, "to": STAGE_FAILED,
                         "reason": f"resume reset failed: {e}"}
                    )
                else:
                    m.stage = STAGE_PENDING
                    self.journal.append(
                        {"event": "resumed", "tenant": tenant,
                         "from": stage, "to": STAGE_PENDING}
                    )
                    self._audit(tenant, "resumed", frm=stage)
            with self._lock:
                self._tenants[tenant] = m
            self._publish_stage(tenant, m.stage)
            out[tenant] = m.stage
        return out

    # -------------------------------------------------- loop + reporting

    def start(self, interval_s: float = 0.25) -> None:
        """Background reconcile loop (the webhook CLI's wiring); tests
        and the bench call tick() directly instead."""
        if self._loop is not None:
            return
        self._stop_evt.clear()

        def _run():
            while not self._stop_evt.is_set():
                try:
                    self.tick()
                except BaseException:  # noqa: BLE001 — incl. ThreadKilled
                    log.exception(
                        "lifecycle loop crashed; controller needs resume()"
                    )
                    return
                self._stop_evt.wait(interval_s)

        self._loop = threading.Thread(
            target=_run, name="lifecycle-controller", daemon=True
        )
        self._loop.start()

    def stop(self) -> None:
        self._stop_evt.set()
        loop, self._loop = self._loop, None
        if loop is not None:
            loop.join(timeout=5.0)
        self.journal.close()

    def stages(self) -> Dict[str, str]:
        with self._lock:
            return {t: m.stage for t, m in self._tenants.items()}

    def status(self) -> dict:
        """The /debug/lifecycle document."""
        with self._lock:
            machines = dict(self._tenants)
        tenants = {}
        for tenant, m in machines.items():
            doc = {
                "stage": m.stage,
                "stage_code": STAGE_CODES[m.stage],
                "promotion": m.spec.promotion,
                "canary_ladder": list(m.spec.canary_ladder),
                "rung": m.rung,
                "attempts": m.attempts,
                "awaiting_approval": m.awaiting_approval,
                "evidence": m.evidence,
            }
            if m.halt is not None:
                doc["halt"] = m.halt
            if m.error is not None:
                doc["last_error"] = m.error
            tenants[tenant] = doc
        return {
            "tenants": tenants,
            "journal": self.journal.path or "memory",
        }


__all__ = [
    "LifecycleController",
    "LifecycleError",
    "STAGE_CODES",
    "STAGE_PENDING",
    "STAGE_VERIFYING",
    "STAGE_SHADOWING",
    "STAGE_CANARY",
    "STAGE_PROMOTING",
    "STAGE_PROMOTED",
    "STAGE_HALTED",
    "STAGE_ROLLED_BACK",
    "STAGE_FAILED",
]
