"""Tenant registry: per-tenant policy sets fused into one shared plane.

The registry owns the tenant → policy-tier mapping and produces the FUSED
tier stack the (single, shared) ``TPUPolicyEngine`` compiles. Fusion works
by cloning: every tenant policy is shallow-cloned ONCE per object
identity, the clone is stamped with its tenant (``_cedar_tenant``, the
side-channel the shard compiler and pack read) and guard-wrapped with the
per-tenant AST condition (compiler/pack.py ``tenant_guard_condition``) so
the interpreter paths isolate tenants exactly like the packed
discriminator literal does. Clones are IDENTITY-STABLE across reloads
while the underlying store object is unchanged — the invariant the shard
differ, the fingerprint memos and the bucket memos key on — so a
one-policy edit in tenant T re-parses one object, produces one fresh
clone, and dirties exactly one ``T/t<tier>b<bucket>`` shard.

Tenant ids are validated (DNS-label-ish, no ``/``): the id is embedded in
shard ids, metrics labels, cache-key scopes and debug documents.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..lang.ast import Policy
from ..lang.authorize import PolicySet

_TENANT_RE = re.compile(r"^[a-z0-9]([a-z0-9._-]{0,62}[a-z0-9])?$", re.I)

__all__ = ["FusedPolicySet", "TenantError", "TenantRegistry"]


class TenantError(ValueError):
    """Invalid tenant id or tenant lifecycle misuse."""


class FusedPolicySet(PolicySet):
    """A PolicySet keyed by (tenant, policy id).

    Two tenants may legitimately carry the same policy id (each authored
    their store independently); the base class would silently overwrite
    one with the other. Reasons still carry the policy's OWN id — fused
    answers must be byte-compatible with the tenant's standalone engine
    (tests/test_tenancy.py pins the differential)."""

    def add(self, p: Policy, policy_id: Optional[str] = None) -> None:
        from ..compiler.pack import policy_tenant

        pid = policy_id or p.policy_id or f"policy{len(self._policies)}"
        p.policy_id = pid
        self._policies[(policy_tenant(p), pid)] = p

    def get(self, policy_id: str) -> Optional[Policy]:
        for p in self._policies.values():
            if p.policy_id == policy_id:
                return p
        return None


class _Tenant:
    __slots__ = (
        "tenant", "tiers_fn", "stores", "clones", "policies", "gen_proxies"
    )

    def __init__(self, tenant: str, tiers_fn, stores):
        self.tenant = tenant
        self.tiers_fn = tiers_fn  # () -> List[PolicySet]
        self.stores = stores  # optional TieredPolicyStores (readiness/gen)
        # id(original) -> (original, clone): the strong ref to the
        # original pins its id for the lifetime of the entry, so an id
        # can never be reused into a false identity hit; entries whose
        # original left the corpus are pruned every fuse pass
        self.clones: Dict[int, Tuple[Policy, Policy]] = {}
        self.policies = 0
        # identity-proxy generation counters for change sources without a
        # content_generation counter (content_fingerprint): key ->
        # [last_seen, counter]; the strong ref pins last_seen so id()
        # reuse after GC can never fake an identity hit
        self.gen_proxies: Dict[object, list] = {}


class TenantRegistry:
    """Thread-safe tenant set + fused-tier assembly (see module doc)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._tenants: Dict[str, _Tenant] = {}
        # bumps on add/remove — folded into content_fingerprint() so the
        # reloader recompiles when the tenant SET changes, not just when
        # some tenant's store contents do
        self._topology_gen = 0
        # identity-stable fused tiers: repeated fused_tiers() calls hand
        # the engine the SAME PolicySet objects until content changes
        # (the store-reuse invariant incremental compilation keys on)
        self._fused_cache: Optional[List[PolicySet]] = None
        self._fused_token: Optional[str] = None
        # set by tenancy.stores.fused_tier_stores: the tier count the
        # wired store stack carries. A later-onboarded tenant with MORE
        # tiers must fail loudly (fused_tiers raises) — a fixed stack
        # would silently never serve the higher tiers' policies
        self.wired_tiers: Optional[int] = None

    # ---------------------------------------------------------- lifecycle

    def add_tenant(
        self,
        tenant: str,
        tiers: Optional[Sequence[PolicySet]] = None,
        tiers_fn: Optional[Callable[[], List[PolicySet]]] = None,
        stores=None,
    ) -> None:
        """Register a tenant. Exactly one of ``tiers`` (a static tier
        stack), ``tiers_fn`` (a provider called per fuse pass) or
        ``stores`` (a TieredPolicyStores — provides tiers, readiness AND
        content generations) must be given."""
        if not _TENANT_RE.match(tenant or ""):
            raise TenantError(
                f"invalid tenant id {tenant!r}: want DNS-label-ish "
                "([a-z0-9._-], no '/', <= 64 chars)"
            )
        provided = sum(x is not None for x in (tiers, tiers_fn, stores))
        if provided != 1:
            raise TenantError(
                "add_tenant: exactly one of tiers/tiers_fn/stores required"
            )
        if tiers is not None:
            static = list(tiers)

            def tiers_fn() -> List[PolicySet]:  # noqa: F811 — closure
                return static

        elif stores is not None:
            def tiers_fn() -> List[PolicySet]:  # noqa: F811 — closure
                analyzed = getattr(stores, "analyzed_policy_sets", None)
                if analyzed is not None:
                    return analyzed()
                return [s.policy_set() for s in stores]

        with self._lock:
            if tenant in self._tenants:
                raise TenantError(f"tenant {tenant!r} already registered")
            self._tenants[tenant] = _Tenant(tenant, tiers_fn, stores)
            self._topology_gen += 1
            self._fused_cache = None

    def remove_tenant(self, tenant: str) -> bool:
        """Offboard a tenant: its policies leave the fused plane at the
        next compile; its shards' disappearance kills its scoped cache
        entries (removed shards drop out of the plane generations)."""
        with self._lock:
            gone = self._tenants.pop(tenant, None) is not None
            if gone:
                self._topology_gen += 1
                self._fused_cache = None
        if gone:
            try:
                from ..server.metrics import clear_tenant_policies

                # drop the departed tenant's policy-count gauge row — a
                # frozen last value would keep counting policies the
                # plane no longer serves
                clear_tenant_policies(tenant)
            except Exception:  # noqa: BLE001 — metrics never break offboard
                pass
        return gone

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def __contains__(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._tenants

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    # ------------------------------------------------------------- fusion

    def _clone(self, entry: _Tenant, p: Policy, seen: set) -> Policy:
        key = id(p)
        seen.add(key)
        hit = entry.clones.get(key)
        if hit is not None and hit[0] is p:
            return hit[1]
        import copy

        from ..compiler.pack import tenant_guard_condition

        q = copy.copy(p)
        # fresh __dict__ rides the copy; strip memo stamps whose value
        # depends on source content — the clone's content INCLUDES the
        # guard, and a stale fingerprint would desync shard hashes across
        # processes (fanout peer-cache wire state compares them)
        q.__dict__.pop("_cedar_content_fp", None)
        q.__dict__.pop("_cedar_ord", None)
        q.conditions = (tenant_guard_condition(entry.tenant),) + tuple(
            p.conditions
        )
        q.__dict__["_cedar_tenant"] = entry.tenant
        entry.clones[key] = (p, q)
        return q

    def fused_tiers(self) -> List[PolicySet]:
        """The fused tier stack: tier i holds every tenant's tier-i
        clones (tenant-sorted for determinism). Tier count is the max
        over tenants; IDENTITY-CACHED until any tenant's content changes
        so repeated reload ticks hand the engine the same objects."""
        with self._lock:
            token = self.content_fingerprint()
            if self._fused_cache is not None and self._fused_token == token:
                return self._fused_cache
            per_tier: Dict[int, List[Policy]] = {}
            n_tiers = 1
            wired = self.wired_tiers
            for tenant in sorted(self._tenants):
                entry = self._tenants[tenant]
                seen: set = set()
                tiers = entry.tiers_fn()
                n_tiers = max(n_tiers, len(tiers))
                count = 0
                for i, ps in enumerate(tiers):
                    bucket = per_tier.setdefault(i, [])
                    for p in ps.policies():
                        bucket.append(self._clone(entry, p, seen))
                        count += 1
                entry.policies = count
                # prune clones whose original left this tenant's corpus
                # (edits replace objects; offboarded files disappear)
                for k in [k for k in entry.clones if k not in seen]:
                    del entry.clones[k]
            if wired is not None and n_tiers > wired:
                raise TenantError(
                    f"fused plane needs {n_tiers} tiers but the wired "
                    f"store stack carries {wired}: re-wire "
                    "fused_tier_stores(registry) before onboarding a "
                    "tenant with more tiers — a fixed stack would "
                    "silently never serve the higher tiers' policies"
                )
            fused = [
                FusedPolicySet(per_tier.get(i, [])) for i in range(n_tiers)
            ]
            self._fused_cache = fused
            self._fused_token = token
            try:
                from ..server.metrics import set_tenant_policies

                for t, e in self._tenants.items():
                    set_tenant_policies(t, e.policies)
            except Exception:  # noqa: BLE001 — metrics never break a fuse
                pass
            return fused

    # ---------------------------------------------------------- readiness

    def ready(self) -> bool:
        """True once every tenant's stores report initial load complete
        (store-less tenants — static tiers — are born ready)."""
        with self._lock:
            entries = list(self._tenants.values())
        for e in entries:
            if e.stores is None:
                continue
            for s in e.stores:
                if not s.initial_policy_load_complete():
                    return False
        return True

    def _proxy_gen(self, entry: _Tenant, key, obj) -> int:
        """Identity-proxy generation counter (the
        TieredPolicyStores.cache_generation pattern): bumps whenever the
        observed object identity changes — reloaders swap set objects on
        content change, so identity moves with content — with a strong
        ref pinning the last-seen object so id() reuse after garbage
        collection can never fake an identity hit. A source that builds
        fresh objects per call bumps every check, which safely disables
        the fused-tier identity cache for that tenant (rebuilt each
        pass, never stale)."""
        with self._lock:
            proxy = entry.gen_proxies.get(key)
            if isinstance(obj, tuple):
                same = (
                    proxy is not None
                    and isinstance(proxy[0], tuple)
                    and len(proxy[0]) == len(obj)
                    and all(a is b for a, b in zip(proxy[0], obj))
                )
            else:
                same = proxy is not None and proxy[0] is obj
            if not same:
                proxy = [obj, (proxy[1] + 1) if proxy else 0]
                entry.gen_proxies[key] = proxy
            return proxy[1]

    def content_fingerprint(self) -> str:
        """Cheap change detector for the reloader: tenant topology + each
        tenant store's content generation. Stores without the counter —
        and provider-fn tenants — contribute an identity-proxy counter
        over their current PolicySet objects (see _proxy_gen), so a
        content swap is ALWAYS detected and the fused plane can never
        keep serving a stale clone set."""
        # snapshot under the lock (the reloader thread calls this while an
        # embedder may be onboarding/offboarding); the store/provider
        # calls below run lock-free on the snapshot (_proxy_gen re-takes
        # the lock only for its table update)
        with self._lock:
            snapshot = dict(self._tenants)
            parts = [f"#{self._topology_gen}"]
        for tenant in sorted(snapshot):
            e = snapshot[tenant]
            if e.stores is not None:
                sub = []
                for i, s in enumerate(e.stores):
                    gen = getattr(s, "content_generation", None)
                    if gen is not None:
                        sub.append(f"{s.name()}@{gen()}")
                    else:
                        g = self._proxy_gen(e, ("store", i), s.policy_set())
                        sub.append(f"{s.name()}@p{g}")
                parts.append(f"{tenant}:{'|'.join(sub)}")
            else:
                sets = tuple(e.tiers_fn())
                parts.append(f"{tenant}:p{self._proxy_gen(e, 'tiers', sets)}")
        return ";".join(parts)

    # -------------------------------------------------------------- debug

    def stats(self) -> dict:
        """Per-tenant rollup for /debug/tenancy and the metrics gauges."""
        with self._lock:
            return {
                "tenants": len(self._tenants),
                "per_tenant": {
                    t: {"policies": e.policies}
                    for t, e in sorted(self._tenants.items())
                },
            }

    @staticmethod
    def shard_prefix(tenant: str) -> str:
        """The shard-id prefix of a tenant's (tenant, tier, bucket)
        shards — what dirty-scope gates and per-tenant rollups match on
        (compiler/shard.py shard_tenant is the inverse)."""
        return f"{tenant}/"
