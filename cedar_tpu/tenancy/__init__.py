"""Multi-tenant shared planes: many clusters' policy sets fused into ONE
TPU dispatch (docs/multitenancy.md).

An AVP-style control plane serves N clusters (tenants) from one device.
Instead of N per-tenant engines at ~1/N duty cycle each (N warm ladders,
N compile caches, N half-empty batches), the :class:`TenantRegistry`
compiles every tenant's policy set through the EXISTING shard pipeline
into one fused plane whose rules carry a tenant-id discriminator literal
(compiler/pack.py ``tenant_literal``) — the slot-match kernel masks
foreign tenants' rules with zero new kernel code — and the
:class:`TenantResolver` front end stamps each request with its tenant id
(path / header / host map) so the existing ``PipelinedBatcher`` coalesces
requests ACROSS tenants into one device dispatch.

Per-tenant lifecycle rides what the shard pipeline already provides,
scoped by tenant: shards are (tenant, tier, bucket), so one tenant's CRD
edit dirties only its own shards, its cache entries die scoped, and its
neighbors' stay warm (the isolation contract a differential test pins,
tests/test_tenancy.py).
"""

from .frontend import TenantBody, TenantResolver
from .registry import TenantError, TenantRegistry
from .stores import fused_tier_stores

__all__ = [
    "TenantBody",
    "TenantError",
    "TenantRegistry",
    "TenantResolver",
    "fused_tier_stores",
]
