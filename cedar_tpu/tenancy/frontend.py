"""Tenant-aware front end: stamp each request with its tenant id.

The webhook server resolves a tenant for every POST (path prefix, header,
or Host/SNI map — in that order) and wraps the raw body in a
:class:`TenantBody`, a ``bytes`` subclass carrying the tenant id. The
whole serving stack passes bodies through opaquely, so the stamp rides
the existing batcher / fleet / fanout plumbing unchanged; the layers that
actually interpret bodies read it back:

  * the native fast path stamps the tenant's feature code into the
    reserved context slot column after the C++ encode
    (engine/fastpath.py — the device then masks foreign tenants' rules);
  * the Python/interpreter paths stamp ``context.tenantId`` into the
    Cedar request (server/authorizer.py);
  * the canonical fingerprint folds the tenant in
    (cache/fingerprint.py), so decision-cache keys, recordings and audit
    lines are tenant-scoped — two tenants' byte-identical SARs can never
    share a cache entry.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

DEFAULT_TENANT_HEADER = "x-cedar-tenant"
DEFAULT_PATH_PREFIX = "/t/"

__all__ = ["DEFAULT_TENANT_HEADER", "TenantBody", "TenantResolver"]


class TenantBody(bytes):
    """A raw webhook body plus the tenant the front end resolved for it.

    Subclassing ``bytes`` keeps every signature on the serving path
    unchanged (C++ encode, json.loads, hashing, slicing into chunks all
    see plain bytes); only tenant-aware layers look for the attribute."""

    tenant: str = ""

    def __new__(cls, data: bytes, tenant: str = "") -> "TenantBody":
        self = super().__new__(cls, data)
        self.tenant = tenant
        return self


class TenantResolver:
    """Maps an incoming request to a registered tenant id.

    Resolution order (first hit wins):
      1. path prefix: ``/t/<tenant>/v1/authorize`` → tenant, with the
         prefix stripped so dispatch sees the canonical ``/v1/...`` path;
      2. header (default ``x-cedar-tenant``, case-insensitive);
      3. host map: exact ``Host``/SNI hostname (port stripped) → tenant —
         the shape a TLS-terminating LB hands multi-SNI traffic over in;
      4. ``default`` tenant, when configured.

    Path and header are CLIENT-SUPPLIED: an operator who authenticates
    tenants out of band (per-tenant SNI/LB routes) must restrict
    ``sources`` to the trusted ones (e.g. ``("host",)``) — otherwise a
    tenant could name a neighbor in the path or header and evaluate
    under its policy slice. When several enabled sources resolve, they
    must AGREE: a host-mapped request whose path/header names a
    different tenant is rejected (``why="conflict"``) instead of letting
    the client-supplied source win over the operator-configured one.

    A resolved-but-UNREGISTERED tenant is rejected (``why="unknown"``) —
    serving an unknown tenant from a plane that has no rules for it would
    silently answer every request NoOpinion and hide the misconfig."""

    SOURCES = ("path", "header", "host")

    def __init__(
        self,
        registry,
        header: str = DEFAULT_TENANT_HEADER,
        path_prefix: str = DEFAULT_PATH_PREFIX,
        hosts: Optional[Dict[str, str]] = None,
        default: Optional[str] = None,
        sources: Optional[Tuple[str, ...]] = None,
    ):
        self.registry = registry
        self.header = (header or DEFAULT_TENANT_HEADER).lower()
        self.path_prefix = path_prefix or DEFAULT_PATH_PREFIX
        self.hosts = {k.lower(): v for k, v in (hosts or {}).items()}
        self.default = default
        srcs = tuple(sources) if sources is not None else self.SOURCES
        bad = [s for s in srcs if s not in self.SOURCES]
        if bad or not srcs:
            raise ValueError(
                f"tenant sources must be a non-empty subset of "
                f"{self.SOURCES}, got {srcs!r}"
            )
        self.sources = srcs

    def _known(self, tenant: str) -> bool:
        try:
            return tenant in self.registry
        except Exception:  # noqa: BLE001 — a sick registry rejects
            return False

    def resolve(
        self, path: str, headers=None, host: Optional[str] = None
    ) -> Tuple[Optional[str], str, str]:
        """(tenant | None, dispatch path, why). ``why`` is the resolution
        source (``path``/``header``/``host``/``default``) or the
        rejection reason (``unknown``/``missing``/``conflict``)."""
        found: Dict[str, str] = {}  # enabled source -> resolved tenant
        out_path = path
        if "path" in self.sources and path.startswith(self.path_prefix):
            rest = path[len(self.path_prefix):]
            seg, sep, tail = rest.partition("/")
            if seg and sep:
                found["path"] = seg
                out_path = "/" + tail
        if "header" in self.sources and headers is not None:
            h = headers.get(self.header)
            if h:
                found["header"] = h.strip()
        if "host" in self.sources and host:
            hkey = host.lower()
            # strip a :port suffix — but a bracketed IPv6 literal without
            # a port ("[::1]") ends in "]" and must not lose its tail
            if ":" in hkey and not hkey.endswith("]"):
                hkey = hkey.rsplit(":", 1)[0]
            mapped = self.hosts.get(hkey)
            if mapped:
                found["host"] = mapped
        if len(set(found.values())) > 1:
            # disagreeing sources: never let a client-supplied path or
            # header override the operator-configured host route
            return None, out_path, "conflict"
        tenant = why = None
        for src in self.sources:
            if src in found:
                tenant, why = found[src], src
                break
        if tenant is None and self.default is not None:
            tenant, why = self.default, "default"
        if tenant is None:
            return None, out_path, "missing"
        if not self._known(tenant):
            return None, out_path, "unknown"
        return tenant, out_path, why

    def describe(self) -> dict:
        """Config document for /debug/tenancy."""
        return {
            "header": self.header,
            "path_prefix": self.path_prefix,
            "hosts": dict(self.hosts),
            "default": self.default,
            "sources": list(self.sources),
        }
