"""Fused tier stores: the registry's tiers as a TieredPolicyStores stack.

The interpreter fallback paths (breaker-open serving, engine-less
deployments, partition non-conformance) and the readiness gates all speak
the store protocol; this module wraps the :class:`TenantRegistry` so the
fused plane's AUTHORIZER is wired exactly like a single-tenant one — the
served PolicySets contain the guard-wrapped clones, so even a pure
interpreter walk over the fused stack is tenant-isolated.
"""

from __future__ import annotations

from typing import List

from ..lang.authorize import PolicySet
from ..stores.store import TieredPolicyStores

__all__ = ["FusedTierStore", "fused_tier_stores"]


class FusedTierStore:
    """One fused tier as a policy store."""

    def __init__(self, registry, tier: int):
        self.registry = registry
        self.tier = tier

    def name(self) -> str:
        return f"tenants/t{self.tier}"

    def policy_set(self) -> PolicySet:
        tiers = self.registry.fused_tiers()
        return tiers[self.tier] if self.tier < len(tiers) else PolicySet([])

    def initial_policy_load_complete(self) -> bool:
        return self.registry.ready()

    def content_generation(self) -> str:
        # strings work everywhere the int counter does: the reloader and
        # cache composites only ever compare for equality
        return self.registry.content_fingerprint()


def fused_tier_stores(registry, n_tiers: int = 0) -> TieredPolicyStores:
    """The registry's fused tier stack as TieredPolicyStores. ``n_tiers``
    0 sizes from the current fused tiers (at least 1). The chosen count
    is stamped on the registry (``wired_tiers``): onboarding a tenant
    with MORE tiers later makes ``fused_tiers()`` raise instead of
    silently never serving the higher tiers through this fixed stack —
    size ``n_tiers`` up front when deeper tenants will onboard live."""
    if n_tiers <= 0:
        n_tiers = max(1, len(registry.fused_tiers()))
    registry.wired_tiers = n_tiers
    stores: List[FusedTierStore] = [
        FusedTierStore(registry, i) for i in range(n_tiers)
    ]
    return TieredPolicyStores(stores)
