"""Consistent-hash ring: canonical fingerprint → worker preference order.

Classic Karger-style ring with virtual nodes: each worker id hashes to
``vnodes`` points on a 64-bit circle, a key routes to the first vnode at
or clockwise of its own hash, and the PREFERENCE ORDER for a key is the
sequence of distinct workers walking clockwise from there. Two
properties the front-end leans on:

  * **Stability** — a key's home worker depends only on the worker-id
    set, never on arrival order or worker count history, so every
    front-end instance (and a restarted one) routes identically;
  * **Minimal movement** — removing a worker reassigns ONLY the keys it
    owned (they fall through to their next preference, which was already
    their spillover target); adding one steals ~1/N of each peer's keys.

blake2b, not Python hash(): hash() is per-process-seeded (PYTHONHASHSEED),
and routing must agree across front-end processes.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence

DEFAULT_VNODES = 64


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big"
    )


class HashRing:
    def __init__(self, workers: Sequence[str] = (), vnodes: int = DEFAULT_VNODES):
        self.vnodes = max(1, int(vnodes))
        self._points: List[int] = []  # sorted vnode positions
        self._owner: Dict[int, str] = {}  # position -> worker id
        self._workers: set = set()
        for w in workers:
            self.add(w)

    def add(self, worker_id: str) -> None:
        if worker_id in self._workers:
            return
        self._workers.add(worker_id)
        for v in range(self.vnodes):
            p = _point(f"{worker_id}#{v}")
            # 64-bit collisions are ~impossible at tier scale; keep the
            # first owner deterministic (sorted) if one ever lands
            if p in self._owner:
                if self._owner[p] < worker_id:
                    continue
            else:
                bisect.insort(self._points, p)
            self._owner[p] = worker_id

    def remove(self, worker_id: str) -> None:
        if worker_id not in self._workers:
            return
        self._workers.discard(worker_id)
        dead = [p for p, w in self._owner.items() if w == worker_id]
        for p in dead:
            del self._owner[p]
            i = bisect.bisect_left(self._points, p)
            if i < len(self._points) and self._points[i] == p:
                del self._points[i]

    @property
    def workers(self) -> set:
        return set(self._workers)

    def preference(self, key: str) -> List[str]:
        """Distinct worker ids in routing order for ``key``: the home
        worker first, then each successive fallback (the rehash target if
        every earlier choice is dead). Deterministic across processes."""
        if not self._points:
            return []
        want = len(self._workers)
        start = bisect.bisect_right(self._points, _point(key))
        out: List[str] = []
        seen = set()
        n = len(self._points)
        for i in range(n):
            w = self._owner[self._points[(start + i) % n]]
            if w not in seen:
                seen.add(w)
                out.append(w)
                if len(out) == want:
                    break
        return out

    def home(self, key: str) -> str:
        pref = self.preference(key)
        if not pref:
            raise LookupError("HashRing: no workers")
        return pref[0]


__all__ = ["DEFAULT_VNODES", "HashRing"]
