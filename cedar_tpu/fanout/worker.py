"""One fanout worker: a full serving stack behind a narrow wire protocol.

A worker is the tier's unit of failure, exactly as a replica is the
fleet's (PR 7): its process can die mid-request, its device plane can
wedge, its policy swap can fail. The front-end only ever talks to the
protocol below, so in-process workers (tests, embedders) and spawned
processes (proc.py, ``bench.py --fanout``) are interchangeable:

  serving   ``authorize(body, request_id)`` → (decision, reason, error)
            ``admit(body)`` → AdmissionReview dict
  control   ``swap(spec)`` / ``restore()`` / ``commit()`` — the
            three-step the front-end's generation barrier drives
            (frontend.py): swap compiles+serves the new set but RETAINS
            the prior one in the worker's own memory, so a barrier
            partial failure restores without anything crossing the wire
  lineage   ``plane_wire()`` — the content-derived plane state
            (cache/generation.py plane_wire_state) the barrier compares
            across the tier
  peering   ``peer_get(key)`` / ``gossip_in(record)`` — the peer cache's
            two calls (peers.py)
  health    ``alive()`` / ``revive()`` / ``stats()``

``InProcessWorker`` runs the stack in the calling process. Its
``kill()``/``revive()`` model a process crash honestly: a killed worker
refuses work until revived, and a revive CLEARS the decision cache — a
restarted process comes back cold, which is exactly why gossip exists.

Chaos: ``fanout.worker_kill`` fires inside every request; a kill rule
marks THIS worker dead mid-request (the in-flight request surfaces
``WorkerDied``, the front-end's cue to rehash and restart).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ..chaos.registry import ThreadKilled, chaos_fire

log = logging.getLogger(__name__)


class WorkerDied(Exception):
    """The worker's process is gone (or modeled gone): the request never
    produced an answer and is safe to re-route — workers are stateless
    between requests, so a rehash can never double-apply anything."""

    def __init__(self, worker_id: str, reason: str = "killed"):
        super().__init__(f"fanout worker {worker_id} died: {reason}")
        self.worker_id = worker_id


class InProcessWorker:
    """See module docstring. ``server`` is a WebhookServer whose HTTP
    listeners are never started — its ``authorize_core``/``admit_core``
    ARE the worker's serving calls, so a worker answers byte-identically
    to a standalone webhook over the same stack. ``tiers_factory``
    resolves a swap spec into a tier stack (in-process specs can simply
    BE the tiers: the default factory is identity)."""

    def __init__(
        self,
        worker_id: str,
        server,
        engine,
        cache=None,
        tiers_factory: Optional[Callable] = None,
        authorizer=None,
    ):
        self.worker_id = worker_id
        self.server = server
        self.engine = engine
        self.cache = cache
        self.authorizer = authorizer
        self.tiers_factory = tiers_factory or (lambda spec: spec)
        self._alive = True
        self._prior = None  # retained pre-swap compiled set (barrier undo)
        self._prior_valid = False
        self._lock = threading.Lock()
        self.requests = 0

    # -------------------------------------------------------------- serving

    def _enter(self) -> None:
        if not self._alive:
            raise WorkerDied(self.worker_id, "not running")
        try:
            chaos_fire("fanout.worker_kill", self.worker_id)
        except ThreadKilled as e:
            # the process-loss model: the worker is gone from here on and
            # the in-flight request dies with it (typed, so the front-end
            # rehashes instead of unwinding)
            self._alive = False
            raise WorkerDied(self.worker_id, str(e)) from e
        self.requests += 1

    def authorize(self, body: bytes, request_id: Optional[str] = None):
        self._enter()
        return self.server.authorize_core(body, request_id)

    def admit(self, body: bytes, request_id: Optional[str] = None) -> dict:
        self._enter()
        return self.server.admit_core(body)

    def supports_admit(self) -> bool:
        """True when this worker's stack can actually EVALUATE admission
        reviews. The front-end refuses to route /v1/admit into a tier
        whose workers lack an admission handler — the worker's fail-mode
        answer would silently replace the outer (working) admission
        stack's real evaluation."""
        return getattr(self.server, "admission_handler", None) is not None

    # -------------------------------------------------------------- control

    def swap(self, spec) -> dict:
        """Compile + serve the policy set ``spec`` resolves to, retaining
        the prior compiled set for ``restore()``. Returns compile stats
        (incl. compile_scope/dirty_shards — incremental when the engine's
        shard cache allows it)."""
        with self._lock:
            tiers = self.tiers_factory(spec)
            prior = self.engine.compiled_set
            stats = self.engine.load(tiers, warm="off")
            self._prior = prior
            self._prior_valid = True
            return stats

    def restore(self) -> bool:
        """Undo the last un-committed swap (barrier partial failure):
        re-adopt the retained prior set compile-free — or clear the
        engine when there was none (first load), never leaving this
        worker serving a generation the tier just refused."""
        with self._lock:
            if not self._prior_valid:
                return False
            if self._prior is None:
                self.engine.clear_compiled()
            else:
                self.engine.adopt_compiled(self._prior)
            self._prior = None
            self._prior_valid = False
            return True

    def commit(self) -> None:
        """The barrier committed tier-wide: drop the retained prior."""
        with self._lock:
            self._prior = None
            self._prior_valid = False

    def plane_wire(self) -> Optional[dict]:
        from ..cache.generation import plane_wire_state

        return plane_wire_state(self.engine)

    # -------------------------------------------------------------- peering

    def peer_get(self, key: str):
        cache = self.cache
        if cache is None or not self._alive:
            return None
        return cache.peer_get(key)

    def gossip_in(self, record: dict):
        cache = self.cache
        if cache is None or not self._alive:
            return False
        return cache.gossip_in(record)

    # --------------------------------------------------------------- health

    def alive(self) -> bool:
        return self._alive

    def kill(self) -> None:
        """Model a process crash (tests/game days)."""
        self._alive = False

    def revive(self) -> bool:
        """Restart the worker. A real process restart loses every
        in-memory decision — the cache is cleared so warmth has to come
        back through traffic and the peer mesh, never by fiat."""
        if self._alive:
            return False
        if self.cache is not None:
            try:
                self.cache.invalidate_all()
            except Exception:  # noqa: BLE001 — a sick cache is an empty cache
                log.exception("worker %s: cache clear on revive failed", self.worker_id)
        self._alive = True
        log.warning("fanout worker %s revived", self.worker_id)
        return True

    def warm_ready(self) -> bool:
        engine = self.engine
        return engine is None or engine.warm_ready()

    def stats(self) -> dict:
        doc = {
            "worker": self.worker_id,
            "alive": self._alive,
            "requests": self.requests,
        }
        if self.engine is not None:
            doc["engine"] = dict(self.engine.stats)
            doc["load_generation"] = self.engine.load_generation
        if self.cache is not None:
            try:
                doc["cache"] = self.cache.stats()
            except Exception:  # noqa: BLE001 — debug must not fail routing
                pass
        return doc

    def stop(self) -> None:
        self._alive = False
        stop = getattr(self.server, "stop_batchers", None)
        if stop is not None:
            try:
                stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.exception("worker %s: batcher stop failed", self.worker_id)


__all__ = ["InProcessWorker", "WorkerDied"]
