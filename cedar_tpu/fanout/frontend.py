"""The fanout front-end: route, supervise, and swap as one logical tier.

Routing: the canonical request fingerprint (the decision cache's own
key, memoized per raw body) walks the consistent-hash ring (ring.py);
the first ALIVE worker in preference order serves. A worker dying
mid-request (``WorkerDied``) re-routes the request to its next
preference — that fall-through IS the rehash, so a worker loss moves
exactly its own keys and nothing else. Dead workers are restarted
supervisor-style (``register_with`` plugs into the PR 6 Supervisor; the
front-end also self-heals inline when no supervisor is wired).

Swaps: ``load()`` / ``promote()`` drive the PR 7 generation barrier over
the control channel — every worker ``swap()``s (retaining its prior set
in its OWN memory) or every worker ``restore()``s; only a tier-wide
success ``commit()``s. After a commit the tier's plane wire states
(worker.plane_wire) must agree — ``status()["coherent"]`` is the
operator's invariant check, and the cross-worker peer cache (peers.py)
refuses records from any worker that drifted.

Raises ``FanoutUnavailable`` when no worker can serve — the caller
(server/http.py) degrades to its interpreter path, exactly like the
fleet's no-replica-admits posture.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Dict, List, Optional

from ..cache.fingerprint import FingerprintMemo
from ..chaos.registry import ThreadKilled, chaos_fire
from .peers import PeerNet
from .ring import HashRing
from .worker import WorkerDied

log = logging.getLogger(__name__)


class FanoutUnavailable(Exception):
    """No fanout worker can serve (all dead / none registered)."""


def _metric(fn_name: str, *args) -> None:
    try:
        from ..server import metrics

        getattr(metrics, fn_name)(*args)
    except Exception:  # noqa: BLE001 — metrics never break routing
        pass


class _WorkerLiveness:
    """Thread-shaped liveness probe for the PR 6 Supervisor (it only
    reads ``is_alive()`` and ``name``): a dead worker process reads as a
    dead thread, so the existing watchdog restarts workers exactly like
    batcher stages."""

    def __init__(self, worker):
        self._worker = worker
        self.name = f"fanout-{worker.worker_id}"

    def is_alive(self) -> bool:
        try:
            return self._worker.alive()
        except Exception:  # noqa: BLE001 — a sick probe reads dead
            return False


class FanoutFrontend:
    def __init__(
        self,
        workers,
        name: str = "fanout",
        vnodes: int = 64,
        memo_capacity: int = 65536,
        peer_fetch: bool = True,
        peer_gossip: bool = True,
    ):
        if not workers:
            raise ValueError("FanoutFrontend: at least one worker required")
        self.name = name
        self.workers: Dict[str, object] = {w.worker_id: w for w in workers}
        if len(self.workers) != len(workers):
            raise ValueError("FanoutFrontend: duplicate worker ids")
        self.ring = HashRing(self.workers, vnodes=vnodes)
        self.net = PeerNet()
        self._memo = FingerprintMemo(capacity=memo_capacity)
        self._adm_memo = FingerprintMemo(capacity=4096)
        self._lock = threading.Lock()  # barrier/topology mutations
        self._swap_epoch = 0
        self._stats_lock = threading.Lock()
        self.routed: Dict[str, int] = {w: 0 for w in self.workers}
        self.reroutes = 0
        self.deaths = 0
        self.restarts = 0
        for w in workers:
            self.net.register(w.worker_id, w)
            cache = getattr(w, "cache", None)
            bind = getattr(cache, "bind", None)
            if bind is not None:
                cache.fetch_enabled = peer_fetch
                cache.gossip_enabled = peer_gossip
                bind(self.net, w.worker_id, order_fn=self.ring.preference)
            _metric("set_fanout_worker_state", self.name, w.worker_id, 1)

    # -------------------------------------------------------------- routing

    def _routing_key(self, endpoint: str, body: bytes) -> str:
        memo = self._memo if endpoint == "authorize" else self._adm_memo
        try:
            fp = memo.fingerprint(endpoint, body)
        except Exception:  # noqa: BLE001 — unparseable routes by raw bytes
            fp = None
        if fp is not None:
            return fp
        # unparseable body: no canonical identity, but routing must still
        # be deterministic so the (error) answer is worker-independent
        return "raw:" + hashlib.sha256(body).hexdigest()

    def _mark_dead(self, worker, reason: str) -> None:
        with self._stats_lock:
            self.deaths += 1
        _metric("set_fanout_worker_state", self.name, worker.worker_id, 0)
        log.warning(
            "fanout %s: worker %s died (%s); rehashing around it",
            self.name,
            worker.worker_id,
            reason,
        )

    def _dispatch(self, endpoint: str, body: bytes, request_id):
        key = self._routing_key(endpoint, body)
        first_choice = True
        for wid in self.ring.preference(key):
            worker = self.workers.get(wid)
            if worker is None:
                continue
            try:
                alive = worker.alive()
            except Exception:  # noqa: BLE001 — a sick probe reads dead
                alive = False
            if not alive:
                first_choice = False
                continue
            try:
                chaos_fire("fanout.route", wid)
            except ThreadKilled as e:
                # route-seam kill: the worker became unreachable at hand-off
                kill = getattr(worker, "kill", None)
                if kill is not None:
                    kill()
                self._mark_dead(worker, str(e))
                first_choice = False
                continue
            if not first_choice:
                with self._stats_lock:
                    self.reroutes += 1
                _metric("record_fanout_reroute", self.name)
            with self._stats_lock:
                self.routed[wid] = self.routed.get(wid, 0) + 1
            _metric("record_fanout_routed", self.name, wid)
            try:
                if endpoint == "authorize":
                    return worker.authorize(body, request_id)
                return worker.admit(body, request_id)
            except WorkerDied as e:
                self._mark_dead(worker, str(e))
                first_choice = False
                continue
        raise FanoutUnavailable(f"fanout {self.name}: no live worker")

    def authorize(self, body: bytes, request_id: Optional[str] = None):
        """(decision, reason, error) from the key's first live worker."""
        return self._dispatch("authorize", body, request_id)

    def admit(self, body: bytes, request_id: Optional[str] = None) -> dict:
        return self._dispatch("admit", body, request_id)

    def supports_admit(self) -> bool:
        """True when every worker can evaluate admission reviews; the
        server routes /v1/admit through the tier only then — an
        admission-less worker would answer its fail-mode (allow, by
        default) instead of evaluating, silently bypassing admission
        enforcement tier-wide."""
        try:
            return all(
                getattr(w, "supports_admit", lambda: False)()
                for w in self.workers.values()
            )
        except Exception:  # noqa: BLE001 — doubt = keep the local stack
            return False

    # ----------------------------------------------- barrier (control channel)

    def load(self, spec, warm: str = "default") -> dict:
        """Reloader target (duck-types TPUPolicyEngine.load): swap the
        tier to the policy set ``spec`` resolves to under the generation
        barrier — every worker serves the new set, or every worker keeps
        (is restored to) its prior one. Incremental per worker: each
        worker's own shard cache diffs the spec, so a one-policy edit
        re-lowers one shard on every worker and the scoped cache stamps
        kill exactly that shard's entries tier-wide."""
        del warm  # workers own their warm policy (swap uses warm="off")
        with self._lock:
            done: List = []
            stats: dict = {}
            try:
                for wid, worker in self.workers.items():
                    chaos_fire("fanout.swap", wid)
                    stats = worker.swap(spec)
                    done.append(worker)
            except BaseException as e:
                for worker in reversed(done):
                    try:
                        worker.restore()
                    except Exception:  # noqa: BLE001 — keep restoring the rest
                        log.exception(
                            "fanout %s: restore of %s after a failed swap "
                            "ALSO failed",
                            self.name,
                            worker.worker_id,
                        )
                log.error(
                    "fanout %s: tier swap failed after %d worker(s); "
                    "restored: %s",
                    self.name,
                    len(done),
                    e,
                )
                raise
            for worker in done:
                # commit is cleanup, not state change: every worker is
                # ALREADY serving the new set, so a failing commit (a
                # wire hiccup on a proc handle) must not unwind the
                # barrier — the worker just retains its prior set until
                # the next swap drops it
                try:
                    worker.commit()
                except Exception:  # noqa: BLE001 — serving state is uniform
                    log.exception(
                        "fanout %s: commit on %s failed (swap already "
                        "serving tier-wide; prior set retained there)",
                        self.name,
                        worker.worker_id,
                    )
            self._swap_epoch += 1
        if not self.plane_coherent():
            # committed but drifted (a worker compiled different content
            # from the same spec): loudly visible — peer sharing already
            # self-protects via wire-state validation
            log.error("fanout %s: tier swap committed INCOHERENT", self.name)
        return stats

    promote = load  # rollout promotion is the same barrier over a new spec

    # ------------------------------------------------------------ lifecycle

    def restart_worker(self, worker_id: str) -> bool:
        """Revive (or respawn, for proc handles) one dead worker and put
        it back in rotation. The restarted worker comes back COLD
        (worker.revive clears its cache) and re-warms from traffic plus
        the peer mesh."""
        worker = self.workers.get(worker_id)
        if worker is None:
            return False
        revive = getattr(worker, "revive", None)
        if revive is None or not revive():
            return False
        with self._stats_lock:
            self.restarts += 1
        _metric("record_fanout_restart", self.name)
        _metric("set_fanout_worker_state", self.name, worker_id, 1)
        # process workers come back on a FRESH peer port: re-announce the
        # mesh tier-wide or the revived worker's cache stays unbound and
        # siblings gossip into the dead port forever (a no-op for
        # in-process workers, whose endpoints are the objects themselves)
        self._rewire_peers()
        # a revived worker may be serving an older plane than the tier
        # (swaps skip dead workers only via barrier failure; a clean kill
        # between swaps needs no catch-up — swap() runs on live workers
        # under the lock). Coherence is checked, not assumed:
        if not self.plane_coherent():
            log.warning(
                "fanout %s: worker %s revived onto a stale plane",
                self.name,
                worker_id,
            )
        return True

    def _rewire_peers(self) -> None:
        """Re-announce the peer mesh to every transport-backed worker
        (ProcWorkerHandle exposes peer_port/peer_config; in-process
        workers talk object-to-object and need nothing)."""
        ports = {
            wid: getattr(w, "peer_port", None)
            for wid, w in self.workers.items()
        }
        ports = {wid: p for wid, p in ports.items() if p}
        if not ports:
            return
        for wid, w in self.workers.items():
            config = getattr(w, "peer_config", None)
            if config is None or not w.alive():
                continue
            try:
                config({k: v for k, v in ports.items() if k != wid})
            except Exception:  # noqa: BLE001 — a dead worker re-meshes later
                log.exception(
                    "fanout %s: peer re-mesh for %s failed", self.name, wid
                )

    def register_with(self, supervisor) -> None:
        """Put every worker under the PR 6 Supervisor: liveness is the
        worker's own alive(), restart is restart_worker — the same
        watchdog loop that revives batcher stages revives workers."""
        for wid, worker in self.workers.items():
            supervisor.register(
                f"fanout.{self.name}",
                threads=lambda w=worker: [_WorkerLiveness(w)],
                restart=lambda reason, i=wid: self.restart_worker(i),
                replica=wid,
            )

    def stop(self) -> None:
        for worker in self.workers.values():
            try:
                worker.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.exception(
                    "fanout %s: worker %s stop failed",
                    self.name,
                    getattr(worker, "worker_id", "?"),
                )

    # --------------------------------------------------------------- status

    def warm_ready(self) -> bool:
        return all(
            w.warm_ready() for w in self.workers.values() if w.alive()
        )

    def alive_workers(self) -> List[str]:
        return [wid for wid, w in self.workers.items() if w.alive()]

    def plane_coherent(self) -> bool:
        """True when every live worker serves the same plane CONTENT
        (wire-state tokens equal). Workers without shard lineage (legacy
        non-incremental engines) read as coherent-unknown = False only
        when they disagree with a lineage-bearing sibling."""
        tokens = set()
        for w in self.workers.values():
            if not w.alive():
                continue
            try:
                wire = w.plane_wire()
            except Exception:  # noqa: BLE001 — unreadable = incoherent
                return False
            tokens.add(wire["token"] if wire else None)
        return len(tokens) <= 1

    def status(self) -> dict:
        """The /debug/fanout document."""
        with self._stats_lock:
            routed = dict(self.routed)
            doc = {
                "fanout": self.name,
                "swap_epoch": self._swap_epoch,
                "reroutes": self.reroutes,
                "deaths": self.deaths,
                "restarts": self.restarts,
            }
        doc["routed"] = routed
        doc["coherent"] = self.plane_coherent()
        doc["workers"] = []
        for wid, w in self.workers.items():
            try:
                stats = w.stats()
            except Exception:  # noqa: BLE001 — debug must not 500
                stats = {"worker": wid, "alive": False, "error": "unreachable"}
            wire = None
            try:
                wire = w.plane_wire()
            except Exception:  # noqa: BLE001
                pass
            if wire is not None:
                stats["plane_token"] = wire["token"][:12]
            doc["workers"].append(stats)
        return doc


__all__ = ["FanoutFrontend", "FanoutUnavailable"]
