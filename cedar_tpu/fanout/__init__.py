"""Cross-process worker tier: one logical webhook spanning N processes.

PR 7's fleet replicates engines inside one process; this package is the
next tier up (ROADMAP open item 1). A lightweight front-end
consistent-hashes canonical request fingerprints (cache/fingerprint.py —
the SAME key the decision cache, recorder, and audit log already share)
onto N webhook workers, each a full serving stack (engine + fast path +
batcher + decision cache). Three properties make the tier one logical
webhook instead of N:

  * **Deterministic routing with rehash-on-death** (ring.py): a
    fingerprint's home worker is stable, so repeat traffic stays warm;
    a dead worker's keys move to their next ring choice and ONLY those
    keys move (consistent hashing), while the front-end restarts the
    worker supervisor-style (PR 6).
  * **A generation barrier over the control channel** (frontend.py):
    policy swaps (reloads, rollout promote/rollback) commit on every
    worker or none — the PR 7 fleet-atomic barrier stretched across
    process boundaries, with the plane's content-derived wire state
    (cache/generation.py plane_wire_state) proving the tier coherent.
  * **A peer-shared decision cache** (peers.py): a repeat SAR hits warm
    on ANY worker. Entries replicate with ShardScopedStamp semantics
    preserved over the wire — keyed on per-shard CONTENT hashes, so an
    incremental adoption kills exactly the changed shard's entries on
    every worker, and nothing process-local ever crosses the wire.

Transports are pluggable: tests and embedders run workers in-process
(worker.py InProcessWorker — isolated stacks, direct calls); ``bench.py
--fanout`` and production spawn real processes (proc.py) speaking the
same protocol over pipes. Chaos seams: ``fanout.route``,
``fanout.worker_kill``, ``fanout.swap``, ``cache.peer_fetch``.
"""

from .frontend import FanoutFrontend, FanoutUnavailable
from .peers import PeerBackedCache, PeerNet
from .ring import HashRing
from .worker import InProcessWorker, WorkerDied

__all__ = [
    "FanoutFrontend",
    "FanoutUnavailable",
    "HashRing",
    "InProcessWorker",
    "PeerBackedCache",
    "PeerNet",
    "WorkerDied",
]
