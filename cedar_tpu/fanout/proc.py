"""Process transport: fanout workers as real OS processes.

``bench.py --fanout`` (and a production single-host deployment) runs
each worker as a spawned process with its own interpreter, JAX runtime,
engine, and decision cache — the GIL-free scaling the in-process flavor
cannot give. The wire protocol is exactly worker.py's:

  * **Serving/control** rides ``channels`` duplex pipes per worker
    (multiprocessing.Pipe, spawn context — never fork: the parent holds
    a live XLA runtime). Each pipe is one in-flight request lane; the
    parent-side handle leases lanes, so per-worker concurrency =
    channels and the worker's own micro-batcher coalesces across lanes.
  * **Peer traffic** rides a localhost TCP mesh: each worker serves
    ``peer_get``/``gossip_in`` as JSON lines on its own port, workers
    get the full port map once the tier is up (``peer_config``), and
    the worker-side PeerNet endpoints are thin TCP clients. Peer records
    are already content-addressed wire dicts (peers.py), so JSON is the
    whole serialization story — nothing process-local crosses.

Worker stacks build from a picklable SPEC (policy source text + serving
knobs) via ``build_worker_stack`` — the same builder the in-process
tests use, so both transports serve byte-identical answers.

A killed process (``ProcWorkerHandle.kill()``, or a real crash) surfaces
as ``WorkerDied`` on every in-flight lane; ``revive()`` respawns the
process from the CURRENT spec — cold cache, same plane — and re-announces
the peer map, mirroring InProcessWorker.revive's cold-restart honesty.
"""

from __future__ import annotations

import json
import logging
import multiprocessing as mp
import os
import socket
import socketserver
import threading
from typing import Dict, List, Optional

from .worker import WorkerDied

log = logging.getLogger(__name__)

_DIED = "__died__"


# --------------------------------------------------------------- worker side


def build_worker_stack(
    spec: dict, worker_id: str, mesh=None, mesh_device_rules=None
):
    """Build one worker's full serving stack from a picklable spec:

      spec["source"]        Cedar policy source text (one tier), or
      spec["synth"]         {"n", "seed", "clusters", "edit_probe"} — a
                            deterministic corpus/synth.py corpus (every
                            worker process regenerates the identical
                            corpus, so the tier's shard hashes agree and
                            ``edit_probe`` is the one-policy CRD edit)
      spec["fastpath"]      wire the native SAR fast path + batcher (default
                            True; falls back when the toolchain is absent)
      spec["cache"]         decision-cache entries (0 disables; default 64k)
      spec["peer_fetch"] / spec["peer_gossip"]   peer-cache modes
      spec["timeout_s"]     per-request deadline budget

    Returns an InProcessWorker (the process wrapper drives it). The
    engine is the authorizer's evaluate backend, so swaps reach the
    served answers on every path — with or without the native fast
    path.

    ``mesh``/``mesh_device_rules`` thread a (data, policy) device mesh
    into the engine — the pod tier (cedar_tpu/pod) builds every host's
    stack through here with the ONE pod-wide mesh, so a "fanout worker"
    and a "pod host" are the same stack pointed at different device
    sets."""
    from ..engine.evaluator import TPUPolicyEngine
    from ..lang import PolicySet
    from ..server.authorizer import CedarWebhookAuthorizer
    from ..server.http import WebhookServer
    from ..stores.store import MemoryStore, TieredPolicyStores
    from .peers import PeerBackedCache
    from .worker import InProcessWorker

    corpus_cache: dict = {}

    def tiers_from(s: dict):
        synth = s.get("synth")
        if synth is not None:
            from ..corpus.synth import synth_corpus

            key = (
                int(synth["n"]),
                int(synth.get("seed", 0)),
                int(synth.get("clusters", 1)),
            )
            base = corpus_cache.get(key)
            if base is None:
                base = corpus_cache[key] = synth_corpus(*key)
            c = base.with_edit() if synth.get("edit_probe") else base
            return c.tiers()
        return [PolicySet.from_source(s["source"], s.get("name", "fanout"))]

    tiers = tiers_from(spec)
    stores = TieredPolicyStores([MemoryStore(f"fanout-{worker_id}", tiers[0])])
    engine = TPUPolicyEngine(
        name=f"fanout-{worker_id}",
        mesh=mesh,
        mesh_device_rules=mesh_device_rules,
    )

    def _eval(entities, request):
        # pre-load / post-clear guard (the CLI's _guarded twin): an
        # engine without a set answers from the tiered stores
        if not engine.loaded:
            return stores.is_authorized(entities, request)
        return engine.evaluate(entities, request)

    def _eval_batch(items):
        if not engine.loaded:
            return [stores.is_authorized(em, r) for em, r in items]
        return engine.evaluate_batch(items)

    authorizer = CedarWebhookAuthorizer(
        stores, evaluate=_eval, evaluate_batch=_eval_batch
    )
    engine.load(tiers, warm="off")

    fastpath = None
    batch_depth = 0
    if spec.get("fastpath", True):
        try:
            from ..engine.fastpath import SARFastPath

            fp = SARFastPath(engine, authorizer)
            if fp.available:
                fastpath = fp
                batch_depth = int(spec.get("pipeline_depth", 2))
        except Exception:  # noqa: BLE001 — no toolchain: interpreter+engine path
            log.exception("worker %s: native fast path unavailable", worker_id)

    cache = None
    cache_entries = int(spec.get("cache", 65536))
    if cache_entries > 0:
        ttls = spec.get("ttls") or {}
        cache = PeerBackedCache(
            max_entries=cache_entries,
            allow_ttl_s=float(ttls.get("allow", 300.0)),
            deny_ttl_s=float(ttls.get("deny", 30.0)),
            no_opinion_ttl_s=float(ttls.get("no_opinion", 5.0)),
            generation_fn=None,  # bound below — needs the engine composite
            fetch_enabled=bool(spec.get("peer_fetch", True)),
            gossip_enabled=bool(spec.get("peer_gossip", True)),
            gossip_async=bool(spec.get("gossip_async", False)),
        )
        from ..cache.generation import plane_composite, plane_wire_state

        cache._generation_fn = lambda: plane_composite(stores, engine)
        cache.wire_state_fn = lambda: plane_wire_state(engine)

    server = WebhookServer(
        authorizer,
        None,
        fastpath=fastpath,
        decision_cache=cache,
        pipeline_depth=batch_depth,
        encode_workers=1,
        request_timeout_s=spec.get("timeout_s"),
    )
    return InProcessWorker(
        worker_id,
        server,
        engine,
        cache=cache,
        tiers_factory=tiers_from,
        authorizer=authorizer,
    )


class _TcpPeer:
    """Worker-side PeerNet endpoint for one sibling: JSON-line calls
    over ONE persistent connection (lock-serialized; reconnect on any
    error). Peer traffic is miss-path-only, but a connect() per miss
    still puts ~ms of handshake on the serving thread — persistent
    beats per-call by an order of magnitude and a dead sibling just
    resets the socket."""

    def __init__(self, port: int):
        self.port = port
        self._lock = threading.Lock()
        self._file = None

    def _connect(self):
        s = socket.create_connection(("127.0.0.1", self.port), timeout=2.0)
        s.settimeout(2.0)
        self._file = s.makefile("rwb")

    def _call(self, payload: dict):
        with self._lock:
            try:
                if self._file is None:
                    self._connect()
                self._file.write(json.dumps(payload).encode() + b"\n")
                self._file.flush()
                line = self._file.readline()
            except (OSError, ValueError):
                self._file = None
                raise
            if not line:
                self._file = None
                raise ConnectionError("peer closed")
            return json.loads(line)

    def peer_get(self, key: str):
        return self._call({"op": "peer_get", "key": key}).get("record")

    def gossip_in(self, record: dict):
        return self._call({"op": "gossip", "record": record}).get("ok", False)


class _PeerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _serve_peers(worker) -> "_PeerServer":
    """Start the worker's peer TCP server on an ephemeral port."""

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            # persistent line protocol: one request per line until the
            # sibling hangs up (matches _TcpPeer's held connection)
            try:
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    req = json.loads(line)
                    if req.get("op") == "peer_get":
                        out = {"record": worker.peer_get(req["key"])}
                    elif req.get("op") == "gossip":
                        out = {"ok": bool(worker.gossip_in(req["record"]))}
                    else:
                        out = {"error": "unknown op"}
                    self.wfile.write(json.dumps(out).encode() + b"\n")
                    self.wfile.flush()
            except Exception:  # noqa: BLE001 — peer serving is best-effort
                log.debug("peer request failed", exc_info=True)

    srv = _PeerServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True, name="peer-srv")
    t.start()
    return srv


def _worker_main(worker_id: str, spec: dict, conns, boot_conn) -> None:
    """Spawned-process entry: build the stack, announce the peer port,
    then serve one request lane per pipe until EOF."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("CEDAR_TPU_WARM_DEFAULT", "off")
    try:
        worker = build_worker_stack(spec, worker_id)
        peer_srv = _serve_peers(worker)
        boot_conn.send(("ready", peer_srv.server_address[1]))
    except Exception as e:  # noqa: BLE001 — the parent must see the failure
        try:
            boot_conn.send(("error", repr(e)))
        finally:
            return

    def control(op: str, payload):
        if op == "peer_config":
            # {sibling id: port} — build the worker-side TCP peer mesh.
            # The ring is rebuilt HERE from the same ids the front-end
            # hashes (ring.py is deterministic across processes), so the
            # home-miss short-circuit, the fetch-order preference, and
            # the gossip fan-out cap all apply inside worker processes
            # exactly as in-process — without them every miss/fill would
            # fan out O(tier) sockets.
            from .peers import PeerNet
            from .ring import HashRing

            net = PeerNet()
            for wid, port in payload.items():
                net.register(wid, _TcpPeer(port))
            if worker.cache is not None:
                ring = HashRing(list(payload) + [worker_id])
                worker.cache.bind(
                    net, worker_id, order_fn=ring.preference
                )
            return True
        if op == "swap":
            return worker.swap(payload)
        if op == "restore":
            return worker.restore()
        if op == "commit":
            worker.commit()
            return True
        if op == "plane_wire":
            return worker.plane_wire()
        if op == "stats":
            return worker.stats()
        if op == "warm_ready":
            return worker.warm_ready()
        raise ValueError(f"unknown control op {op!r}")

    def lane(conn):
        while True:
            try:
                op, payload = conn.recv()
            except (EOFError, OSError):
                return
            try:
                if op == "authorize":
                    out = ("ok", worker.authorize(payload))
                elif op == "admit":
                    out = ("ok", worker.admit(payload))
                elif op == "stop":
                    conn.send(("ok", True))
                    os._exit(0)
                else:
                    out = ("ok", control(op, payload))
            except WorkerDied as e:
                out = (_DIED, str(e))
            except Exception as e:  # noqa: BLE001 — the lane must answer
                out = ("err", repr(e))
            try:
                conn.send(out)
            except (OSError, BrokenPipeError):
                return

    threads = [
        threading.Thread(target=lane, args=(c,), daemon=True, name=f"lane{i}")
        for i, c in enumerate(conns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


# --------------------------------------------------------------- parent side


class ProcWorkerHandle:
    """Parent-side worker handle speaking the worker protocol over the
    pipes — a drop-in for InProcessWorker in FanoutFrontend."""

    def __init__(self, worker_id: str, spec: dict, channels: int = 4):
        self.worker_id = worker_id
        self.spec = dict(spec)
        self.channels = max(1, int(channels))
        self.peer_port: Optional[int] = None
        self.cache = None  # parent side holds no cache; peers are TCP
        self._pending_spec: Optional[dict] = None
        self._dead = False
        self._lock = threading.Lock()
        self._spawn()

    def _spawn(self) -> None:
        ctx = mp.get_context("spawn")
        pairs = [ctx.Pipe(duplex=True) for _ in range(self.channels)]
        boot_parent, boot_child = ctx.Pipe(duplex=True)
        self._conns = [p for p, _c in pairs]
        self._free: List = list(self._conns)
        self._free_cv = threading.Condition()
        self._lanes_lost = 0
        self._proc = ctx.Process(
            target=_worker_main,
            args=(self.worker_id, self.spec, [c for _p, c in pairs], boot_child),
            daemon=True,
            name=f"fanout-{self.worker_id}",
        )
        self._proc.start()
        boot_child.close()
        for _p, c in pairs:
            c.close()
        if not boot_parent.poll(180):
            raise RuntimeError(f"worker {self.worker_id}: boot timeout")
        status, payload = boot_parent.recv()
        if status != "ready":
            raise RuntimeError(f"worker {self.worker_id}: boot failed: {payload}")
        self.peer_port = payload
        self._dead = False

    def _call(self, op: str, payload, timeout: float = 120.0):
        if self._dead:
            raise WorkerDied(self.worker_id, "not running")
        with self._free_cv:
            while not self._free:
                if not self._free_cv.wait(timeout):
                    raise TimeoutError(f"worker {self.worker_id}: no free lane")
            conn = self._free.pop()
        # a lane whose request TIMED OUT still has a reply in flight: it
        # must never return to the pool, or the next request on it would
        # read the PREVIOUS operation's answer (cross-request corruption).
        # Abandoning it sheds one lane of capacity; a worker that times
        # out every lane stops being callable and reads dead.
        poisoned = False
        try:
            conn.send((op, payload))
            if not conn.poll(timeout):
                poisoned = True
                raise WorkerDied(self.worker_id, f"{op} timeout")
            status, result = conn.recv()
        except (EOFError, OSError, BrokenPipeError) as e:
            self._dead = True
            poisoned = True
            raise WorkerDied(self.worker_id, f"pipe: {e}") from e
        finally:
            with self._free_cv:
                if not poisoned:
                    self._free.append(conn)
                    self._free_cv.notify()
                else:
                    self._lanes_lost += 1
                    if self._lanes_lost >= self.channels:
                        # every lane abandoned: the worker is effectively
                        # unreachable — read dead so the ring rehashes
                        self._dead = True
        if status == _DIED:
            self._dead = True
            raise WorkerDied(self.worker_id, result)
        if status == "err":
            raise RuntimeError(f"worker {self.worker_id}: {result}")
        return result

    # ------------------------------------------------------ worker protocol

    def authorize(self, body: bytes, request_id=None):
        res = self._call("authorize", body)
        return tuple(res)

    def admit(self, body: bytes, request_id=None) -> dict:
        return self._call("admit", body)

    def supports_admit(self) -> bool:
        # build_worker_stack carries no admission stack yet; the front
        # end must keep /v1/admit on the local evaluator (http.py)
        return False

    def swap(self, spec) -> dict:
        out = self._call("swap", spec)
        # remember the candidate only after the worker accepted it; a
        # respawn must come back on whatever the barrier COMMITS
        self._pending_spec = dict(spec)
        return out

    def restore(self) -> bool:
        self._pending_spec = None
        return bool(self._call("restore", None))

    def commit(self) -> None:
        pending = getattr(self, "_pending_spec", None)
        if pending is not None:
            self.spec = pending  # a respawn comes back on the committed set
            self._pending_spec = None
        self._call("commit", None)

    def plane_wire(self):
        return self._call("plane_wire", None)

    def peer_config(self, port_map: Dict[str, int]) -> None:
        self._call("peer_config", port_map)

    def peer_get(self, key: str):  # parent-side peers unused (TCP mesh)
        return None

    def gossip_in(self, record: dict) -> bool:
        return False

    def warm_ready(self) -> bool:
        try:
            return bool(self._call("warm_ready", None, timeout=30))
        except WorkerDied:
            return True  # dead workers don't gate readiness

    def stats(self) -> dict:
        try:
            return self._call("stats", None, timeout=30)
        except WorkerDied:
            return {"worker": self.worker_id, "alive": False}

    def alive(self) -> bool:
        return not self._dead and self._proc.is_alive()

    def kill(self) -> None:
        """Hard process kill (bench/game days): SIGKILL, no goodbye."""
        self._dead = True
        try:
            self._proc.kill()
            self._proc.join(10)
        except Exception:  # noqa: BLE001 — it is dead either way
            pass

    def revive(self) -> bool:
        if self.alive():
            return False
        try:
            self._proc.join(5)
        except Exception:  # noqa: BLE001
            pass
        self._spawn()
        return True

    def stop(self) -> None:
        if not self._dead and self._proc.is_alive():
            try:
                self._call("stop", None, timeout=10)
            except Exception:  # noqa: BLE001 — force below
                pass
        self._dead = True
        try:
            self._proc.join(5)
            if self._proc.is_alive():
                self._proc.kill()
                self._proc.join(5)
        except Exception:  # noqa: BLE001 — teardown must finish
            pass


def wire_peer_mesh(handles: List[ProcWorkerHandle]) -> None:
    """Announce the full {worker id: peer port} map to every worker —
    call once after all workers booted, and again after any revive."""
    ports = {h.worker_id: h.peer_port for h in handles if h.peer_port}
    for h in handles:
        if h.alive():
            try:
                h.peer_config({w: p for w, p in ports.items() if w != h.worker_id})
            except Exception:  # noqa: BLE001 — a dead worker re-meshes at revive
                log.exception("peer mesh config for %s failed", h.worker_id)


__all__ = ["ProcWorkerHandle", "build_worker_stack", "wire_peer_mesh"]
