"""Peer-shared decision cache: a repeat SAR hits warm on any worker.

Every worker already runs the PR 3 DecisionCache keyed on canonical
fingerprints with PR 11's shard-scoped generation stamps. This module
stretches those exact semantics across workers without letting anything
process-local cross the wire:

  * a **wire record** carries (key, value, decision class, remaining
    TTL) plus the entry's scope translated to CONTENT terms — the
    determining shards' per-shard content hashes for a ShardScopedStamp,
    or the whole plane's wire token for an unscoped entry
    (cache/generation.py plane_wire_state). Shard generation numbers and
    structural plane ids are per-process counters and never leave the
    process;
  * the **receiver re-derives a local stamp**: it accepts a record only
    when its OWN serving plane carries the same content for every named
    shard (or the same whole-plane token), then stamps the entry with
    its own live PlaneGenerations scoped to those shards. From that
    moment the entry lives under the receiver's normal invalidation
    rules — an incremental adoption on ANY worker's next reload kills
    exactly the changed shard's replicated entries, because the barrier
    (frontend.py) lands the same content change on every worker;
  * **TTL rides along and only ever shrinks** (DecisionCache.put ttl_s):
    replication cannot restart the staleness clock, so the documented
    cross-shard staleness bound (docs/caching.md) holds tier-wide.

Two replication paths share the validation: **peer fetch** (on a local
miss, ask the key's ring-preferred holders — the spillover/rehash warm
path) and **gossip** (on a local miss-fill, push the record to peers —
what makes a worker-kill rehash land on already-warm successors). Both
ride the ``cache.peer_fetch`` chaos seam; a sick peer costs a miss,
never an answer.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from ..cache.decision_cache import DecisionCache, _UNSET
from ..cache.generation import PlaneGenerations, ShardScopedStamp
from ..chaos.registry import ThreadKilled, chaos_fire

log = logging.getLogger(__name__)


def _record_metric(path: str, event: str, n: int = 1) -> None:
    try:
        from ..server.metrics import record_peer_cache

        record_peer_cache(path, event, n)
    except Exception:  # noqa: BLE001 — metrics never break peer traffic
        pass


class PeerNet:
    """The worker-to-worker transport (in-process flavor): a registry of
    peer endpoints — objects exposing ``peer_get(key)`` and
    ``gossip_in(record)``. The proc transport (proc.py) registers handles
    that speak the same two calls over the worker's pipe, so the cache
    logic never knows which deployment it is in."""

    def __init__(self, path: str = "authorization"):
        self.path = path
        self._peers: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, worker_id: str, endpoint) -> None:
        with self._lock:
            self._peers[worker_id] = endpoint

    def unregister(self, worker_id: str) -> None:
        with self._lock:
            self._peers.pop(worker_id, None)

    def _peer(self, worker_id: str):
        with self._lock:
            return self._peers.get(worker_id)

    def peer_ids(self) -> List[str]:
        with self._lock:
            return list(self._peers)

    def fetch(
        self, requester_id: str, key: str, order: Optional[List[str]] = None
    ) -> Optional[dict]:
        """Ask peers for ``key`` in ``order`` (the ring preference — the
        home worker is the likeliest holder); first wire record wins.
        Containment: ANY peer failure (including an injected kill — the
        process-loss analogue) skips that peer."""
        ids = [w for w in (order or self.peer_ids()) if w != requester_id]
        for wid in ids:
            ep = self._peer(wid)
            if ep is None:
                continue
            try:
                chaos_fire("cache.peer_fetch", ("fetch", requester_id, wid))
                rec = ep.peer_get(key)
            except (Exception, ThreadKilled):  # noqa: BLE001 — peer = best-effort
                log.debug("peer fetch from %s failed", wid, exc_info=True)
                continue
            if rec is not None:
                return rec
        return None

    def gossip(
        self,
        origin_id: str,
        record: dict,
        targets: Optional[List[str]] = None,
    ) -> int:
        """Push one wire record to ``targets`` (default: every other
        peer); returns deliveries."""
        n = 0
        for wid in targets if targets is not None else self.peer_ids():
            if wid == origin_id:
                continue
            ep = self._peer(wid)
            if ep is None:
                continue
            try:
                chaos_fire("cache.peer_fetch", ("gossip", origin_id, wid))
                ep.gossip_in(record)
                n += 1
            except (Exception, ThreadKilled):  # noqa: BLE001 — best-effort
                log.debug("gossip to %s failed", wid, exc_info=True)
        return n


class PeerBackedCache(DecisionCache):
    """A DecisionCache that replicates through a PeerNet (module
    docstring). Construct like a DecisionCache, then ``bind()`` it to
    the net once the tier exists; unbound it behaves exactly like its
    base class."""

    def __init__(
        self,
        *args,
        wire_state_fn: Optional[Callable[[], Optional[dict]]] = None,
        fetch_enabled: bool = True,
        gossip_enabled: bool = True,
        gossip_async: bool = False,
        fetch_limit: int = 2,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        # () -> plane_wire_state(engine) for THIS worker's serving plane
        self.wire_state_fn = wire_state_fn
        self.fetch_enabled = fetch_enabled
        self.gossip_enabled = gossip_enabled
        # gossip_async moves replication OFF the serving thread: records
        # queue (bounded, shed-oldest) and a daemon drains them to peers.
        # Default synchronous — deterministic for in-process tiers/tests;
        # the process transport turns this on (a miss-fill must not pay
        # N-1 socket round trips inline).
        self.gossip_async = gossip_async
        # how many ring-preferred peers a miss may ask before giving up:
        # the home worker is overwhelmingly the holder, and walking the
        # whole tier would put O(workers) sockets on the miss path
        self.fetch_limit = max(1, int(fetch_limit))
        # how many ring-successors of a key receive its gossip: the
        # rehash-warmth property needs exactly the workers a dead home's
        # keys would land on, not the whole tier (O(N) sockets per fill)
        self.gossip_fanout = 2
        self._gossip_q: "deque" = deque(maxlen=1024)
        self._gossip_wake = threading.Event()
        self._gossip_thread: Optional[threading.Thread] = None
        self._net: Optional[PeerNet] = None
        self.worker_id = ""
        self._order_fn: Optional[Callable[[str], List[str]]] = None
        # keys whose live entry came from a peer (fetch or gossip): a hit
        # on one is a CROSS-WORKER hit — the tier-level warmth signal the
        # fanout bench gates on. Bounded: reset when it outgrows the
        # cache (stale members only misclassify a re-filled key's first
        # hits, never correctness).
        self._peer_keys: set = set()
        self._stats_lock = threading.Lock()
        self.peer_stats = {
            "fetches": 0,
            "fetch_hits": 0,
            "gossip_out": 0,
            "gossip_in": 0,
            "stale_dropped": 0,
            "peer_served": 0,
        }

    def bind(self, net: PeerNet, worker_id: str, order_fn=None) -> None:
        self._net = net
        self.worker_id = worker_id
        self._order_fn = order_fn
        if self.gossip_async and self._gossip_thread is None:
            t = threading.Thread(
                target=self._gossip_drain,
                daemon=True,
                name=f"gossip-{worker_id}",
            )
            self._gossip_thread = t
            t.start()

    def _gossip_drain(self) -> None:
        while True:
            self._gossip_wake.wait()
            self._gossip_wake.clear()
            while True:
                try:
                    rec, targets = self._gossip_q.popleft()
                except IndexError:
                    break
                net = self._net
                if net is None:
                    continue
                try:
                    self._count(
                        "gossip_out",
                        net.gossip(self.worker_id, rec, targets),
                    )
                except Exception:  # noqa: BLE001 — replication is best-effort
                    log.debug("gossip drain failed", exc_info=True)

    def _count(self, event: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._stats_lock:
            self.peer_stats[event] += n
        _record_metric(self.path, event, n)

    # ------------------------------------------------------------ wire out

    def _to_wire(self, key: str, value, decision_class: str, stamp) -> Optional[dict]:
        wire = self.wire_state_fn() if self.wire_state_fn else None
        if wire is None:
            return None
        rec = {
            "key": key,
            "value": value,
            "class": decision_class,
            "ttl": self.ttl_for(decision_class),
        }
        if isinstance(stamp, ShardScopedStamp):
            shards = {}
            for sid, _gen in stamp.shard_gens:
                h = wire["shards"].get(sid)
                if h is None:  # lineage drifted mid-flight: full scope
                    rec["token"] = wire["token"]
                    return rec
                shards[sid] = h
            rec["shards"] = shards
        else:
            rec["token"] = wire["token"]
        return rec

    def peer_get(self, key: str) -> Optional[dict]:
        """Serve one entry to a sibling worker as a wire record (or None).
        Freshness is judged by THIS worker's own rules (peer_lookup), and
        the remaining TTL rides the record so the receiver's clock starts
        where ours left off."""
        got = self.peer_lookup(key)
        if got is None:
            return None
        value, decision_class, stamp, ttl_left = got
        rec = self._to_wire(key, value, decision_class, stamp)
        if rec is None:
            return None
        rec["ttl"] = ttl_left
        return rec

    # ------------------------------------------------------------- wire in

    def _local_stamp(self, record: dict):
        """Validate a wire record against THIS worker's serving plane and
        return the local generation stamp to store it under, or None when
        the record describes content this plane does not serve."""
        wire = self.wire_state_fn() if self.wire_state_fn else None
        if wire is None:
            return None
        gen = self.current_generation()
        shards = record.get("shards")
        if shards:
            for sid, h in shards.items():
                if wire["shards"].get(sid) != h:
                    return None
            if isinstance(gen, PlaneGenerations):
                gens = []
                for sid in sorted(shards):
                    g = gen.shards.get(sid)
                    if g is None:
                        return None
                    gens.append((sid, g))
                return ShardScopedStamp(gen.base, tuple(gens))
            return None  # content matches but no local lineage: reject
        if record.get("token") != wire["token"]:
            return None
        return gen

    def _accept(self, record: dict, event: str) -> bool:
        stamp = self._local_stamp(record)
        if stamp is None:
            self._count("stale_dropped")
            return False
        ttl = record.get("ttl")
        ok = DecisionCache.put(
            self,
            record["key"],
            record["value"],
            record["class"],
            generation=stamp,
            ttl_s=ttl,
        )
        if ok:
            self._peer_keys.add(record["key"])
            if len(self._peer_keys) > 2 * self.max_entries:
                self._peer_keys = {record["key"]}
            self._count(event)
        return ok

    def gossip_in(self, record: dict) -> bool:
        return self._accept(record, "gossip_in")

    # ------------------------------------------------------------- surface

    def get(self, key: str):
        value = super().get(key)
        if value is not None:
            if key in self._peer_keys:
                self._count("peer_served")
            return value
        net = self._net
        if net is None or not self.fetch_enabled:
            return None
        order = self._order_fn(key) if self._order_fn else None
        if order and order[0] == self.worker_id:
            # this worker IS the key's ring home: gossip replicates every
            # fill here too, so a home-side miss is (races aside) a
            # tier-wide miss — asking peers would put socket round trips
            # into busy siblings on the common miss path for nothing.
            # Fetch earns its cost exactly when this worker is a
            # SPILLOVER/rehash target and the home (or a gossip-warmed
            # sibling) holds the entry.
            return None
        if order is not None:
            order = order[: self.fetch_limit + 1]  # +1: self may lead it
        self._count("fetches")
        rec = net.fetch(self.worker_id, key, order)
        if rec is None or rec.get("key") != key:
            return None
        if not self._accept(rec, "fetch_hits"):
            return None
        return rec["value"]

    def put(
        self, key: str, value, decision_class: str, generation=_UNSET, ttl_s=None
    ) -> bool:
        ok = super().put(
            key, value, decision_class, generation=generation, ttl_s=ttl_s
        )
        if ok:
            # a LOCAL fill supersedes any peer-origin residue: hits on it
            # are this worker's own warmth, not cross-worker serving
            self._peer_keys.discard(key)
        net = self._net
        if ok and net is not None and self.gossip_enabled:
            # a local miss-fill is fresh tier-wide knowledge: push it so a
            # rehash (worker death) lands on already-warm successors
            stamp = None if generation is _UNSET else generation
            if stamp is not None:
                rec = self._to_wire(key, value, decision_class, stamp)
                if rec is not None:
                    targets = None
                    if self._order_fn is not None:
                        targets = [
                            w
                            for w in self._order_fn(key)
                            if w != self.worker_id
                        ][: self.gossip_fanout]
                    if self.gossip_async:
                        # shed-oldest when full
                        self._gossip_q.append((rec, targets))
                        self._gossip_wake.set()
                    else:
                        self._count(
                            "gossip_out",
                            net.gossip(self.worker_id, rec, targets),
                        )
        return ok

    def stats(self) -> dict:
        out = super().stats()
        with self._stats_lock:
            out["peer"] = dict(self.peer_stats)
        out["peer"]["worker"] = self.worker_id
        return out


__all__ = ["PeerBackedCache", "PeerNet"]
